"""ScenarioFleet: the fused robust-MPC round over (agents × scenarios).

The reference can only solve a scenario tree branch by branch — one
CasADi+IPOPT process call per branch per agent per iteration. Here the
scenario axis is batched and sharded exactly like the agent axis
(PR 9): each agent vmaps its interior-point solve over S disturbance
branches inside the fused ADMM ``while_loop``, and the two couplings
each lower to ONE ``lax.psum`` family per iteration on a 2-D mesh:

* **agents** — the ADMM consensus/residual reductions of the PR 9
  fleet, per scenario (``psum`` over the ``"agents"`` axis);
* **scenarios** — the non-anticipativity projection: scenarios sharing
  a tree node must apply the same robust-horizon controls, enforced as
  consensus-ADMM onto the node-group mean (``psum`` over the
  ``"scenarios"`` axis) with per-branch multipliers. The actuated
  ``u0`` IS the projected group mean — identical across a group's
  branches by construction, not by luck.

Certification end-to-end (PR 11): mesh engines trace the fused round at
build time and prove the two-family schedule with the per-axis
replication lattice — the nested residual psums (agents, then
scenarios) re-replicate the Boyd exit predicate, which the certifier
now follows axis by axis. A refuted schedule refuses to dispatch on a
multi-process mesh; the degenerate single-scenario engine (no
non-anticipativity terms traced at all) certifies the same one-family
shape as today's agent fleet.

Scope: one structure group per fleet (heterogeneous robust fleets
bucket one ScenarioFleet per structure, like the serving plane buckets
FusedADMM engines); the shared-trace two-phase solve (cold budget at
iteration 0, warm after) is the only solver wiring — per-phase option
structures beyond budget/barrier belong to :class:`FusedADMM`.
"""

from __future__ import annotations

import logging
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.ops import admm as admm_ops
from agentlib_mpc_tpu.ops.admm import AdmmResiduals, consensus_penalty
from agentlib_mpc_tpu.ops.solver import (
    NLPFunctions,
    solve_nlp,
)
from agentlib_mpc_tpu.scenario.tree import ScenarioTree
from agentlib_mpc_tpu.telemetry.profiler import phase_scope

logger = logging.getLogger(__name__)

__all__ = [
    "ScenarioFleet",
    "ScenarioFleetOptions",
    "ScenarioState",
    "ScenarioStats",
    "pad_scenarios",
    "solve_nlp_scenarios",
]


def solve_nlp_scenarios(nlp, w0_batch, theta_batch, lb_batch, ub_batch,
                        options, tree: "ScenarioTree | None" = None,
                        y0_batch=None, z0_batch=None):
    """S independent per-branch solves as one scenario-batched call
    (leading axis S on every array / theta leaf). The degenerate S=1
    batch routes through :func:`~agentlib_mpc_tpu.ops.solver.solve_nlp`
    UNWRAPPED — not a 1-lane vmap — so its result is bit-identical to
    the flat solver path (the ISSUE 12 degenerate-tree contract);
    S > 1 is the plain vmap the fused fleet uses."""
    S = int(w0_batch.shape[0])
    if tree is not None and tree.n_scenarios != S:
        raise ValueError(
            f"w0_batch carries {S} scenarios, tree has "
            f"{tree.n_scenarios}")
    if S == 1:
        row = lambda leaf: None if leaf is None else leaf[0]
        res = solve_nlp(nlp, w0_batch[0],
                        jax.tree.map(lambda l: l[0], theta_batch),
                        lb_batch[0], ub_batch[0], options,
                        y0=row(y0_batch), z0=row(z0_batch))
        return jax.tree.map(lambda leaf: jnp.asarray(leaf)[None], res)
    if y0_batch is None:
        return jax.vmap(lambda w0, th, lb, ub: solve_nlp(
            nlp, w0, th, lb, ub, options))(w0_batch, theta_batch,
                                           lb_batch, ub_batch)
    return jax.vmap(lambda w0, th, lb, ub, y0, z0: solve_nlp(
        nlp, w0, th, lb, ub, options, y0=y0, z0=z0))(
        w0_batch, theta_batch, lb_batch, ub_batch, y0_batch, z0_batch)


class ScenarioFleetOptions(NamedTuple):
    max_iterations: int = 20
    #: consensus penalty for the agent couplings (one value for every
    #: alias — per-alias adaptation stays with :class:`FusedADMM`)
    rho: float = 10.0
    #: non-anticipativity penalty over the scenario groups
    rho_na: float = 10.0
    #: Boyd relative-tolerance exit (same semantics as FusedADMMOptions)
    abs_tol: float = 1e-3
    rel_tol: float = 1e-2
    use_relative_tolerances: bool = True
    primal_tol: float = 1e-3
    dual_tol: float = 1e-3
    #: warm-phase inner interior-point budget (traced; iteration 0 runs
    #: the group's full cold budget — the shared-trace two-phase scheme)
    warm_budget: int = 6
    #: warm-phase initial barrier
    warm_mu: float = 1e-2
    #: quarantine non-finite per-branch solutions inside the jitted
    #: loop (the FusedADMM pattern at (agent, scenario) granularity): a
    #: diverged branch is replaced by its previous iterate via
    #: ``jnp.where`` — purely elementwise, so the certified collective
    #: schedule (and the [jaxpr.collectives.scenario] psum pins) is
    #: unchanged
    quarantine: bool = True
    #: consecutive quarantined iterations before a branch's warm start
    #: is reset to the (sanitized) OCP initial guess
    quarantine_reset_after: int = 3


class ScenarioState(NamedTuple):
    """Carried between control steps (the robust warm-start memory).
    Agent axes are shard-local under a mesh; the scenario axis likewise."""

    zbar: dict          # alias -> (S, T) per-scenario consensus means
    lam: dict           # alias -> (n_agents, S, T) multipliers
    nu: jnp.ndarray     # (n_agents, S, R, n_u) non-anticipativity mult.
    na_target: jnp.ndarray  # (n_agents, S, R, n_u) last group-mean proj.
    w: jnp.ndarray      # (n_agents, S, n_w) primal warm starts
    y: jnp.ndarray      # (n_agents, S, n_g)
    z: jnp.ndarray      # (n_agents, S, n_h)


class ScenarioStats(NamedTuple):
    iterations: jnp.ndarray           # ()
    primal_residuals: jnp.ndarray     # (max_iter,) NaN-padded
    dual_residuals: jnp.ndarray
    converged: jnp.ndarray            # () bool
    local_solves_ok: jnp.ndarray      # () bool
    #: final non-anticipativity primal residual — how far the branch
    #: controls sit from their group projection (the ``scenario_spread``
    #: telemetry histogram; exactly 0 when the tree has no coupling)
    na_spread: jnp.ndarray            # ()
    #: PER-BRANCH quarantine attribution: (n_agents, S) int32 — how
    #: many of the round's iterations each (agent, scenario) lane spent
    #: quarantined. The substitution keeps a sick branch's decoded
    #: trajectory finite, so this column is the ONLY signal that a
    #: branch diverged every iteration — the serving health ledger's
    #: third sickness signal on robust tenants (ISSUE 14 satellite).
    #: None when the fleet was built with ``quarantine=False``.
    lane_quarantined: "jnp.ndarray | None" = None


class ScenarioFleet:
    """Compiled robust-MPC round: one structure group × S disturbance
    scenarios, batched (vmap) or sharded (2-D ``shard_map``) over both
    axes. Build once per (group structure, tree); call :meth:`step`
    once per control step with a (n_agents, S)-leading theta batch."""

    def __init__(self, group, tree: ScenarioTree,
                 options: ScenarioFleetOptions = ScenarioFleetOptions(),
                 active=None, mesh=None,
                 collective_certify: str = "auto",
                 memory_certify: str = "auto",
                 dispatch_certify: str = "auto",
                 precision_certify: str = "auto",
                 watchdog_timeout_s: "float | None" = None,
                 warmstart=None):
        """``group``: an :class:`~agentlib_mpc_tpu.parallel.fused_admm.
        AgentGroup` (couplings only; exchanges are not scenario-lifted).
        ``tree``: the static scenario tree; ``tree.n_scenarios == 1``
        builds the degenerate engine — no non-anticipativity terms are
        traced, so the schedule is exactly today's one-family fleet.
        ``mesh``: None (single device), a 1-D ``("agents",)`` mesh, or
        a 2-D ``("agents", "scenarios")`` mesh
        (:func:`~agentlib_mpc_tpu.parallel.multihost.scenario_mesh`).
        ``collective_certify``: "auto" | "require" | "off", the
        :class:`FusedADMM` policy verbatim. ``memory_certify``: same
        vocabulary for the static per-device peak-bytes certificate
        (:mod:`agentlib_mpc_tpu.lint.jaxpr.memory`) — the scenario axis
        multiplies every lane buffer by S, which is exactly the
        projection the certificate prices before a robust fleet can
        OOM a pod dispatch. ``precision_certify``: same vocabulary for
        the per-phase error-growth certificate
        (:mod:`agentlib_mpc_tpu.lint.jaxpr.precision`) behind
        ``SolverOptions.precision`` — certified under ``"auto"`` only
        when the group actually resolves to the mixed path; a refuted
        or unprovable certificate raises when the group demanded
        ``precision="require"``. ``watchdog_timeout_s``: arm the COLLECTIVE
        watchdog — every 2-D round runs on a bounded reader (the
        :class:`FusedADMM` pattern on both axes); a blown budget
        condemns the mesh, records a bounded per-device probe on
        ``self.shard_report`` and raises
        :class:`~agentlib_mpc_tpu.parallel.multihost.MeshRoundTimeout`
        so :class:`~agentlib_mpc_tpu.parallel.survival.
        ScenarioFleetSupervisor` can classify the loss by axis.
        ``warmstart``: an optional learned warm-start document
        (:class:`~agentlib_mpc_tpu.ml.serialized.SerializedWarmstart`)
        or prebuilt bundle — cold starts in :meth:`init_state` come
        from the in-graph gated prediction per (agent, scenario) lane;
        a fingerprint mismatch with the group's structure raises
        :class:`~agentlib_mpc_tpu.ml.warmstart.WarmstartDriftError`."""
        from agentlib_mpc_tpu.parallel.fused_admm import FusedADMM

        if group.exchanges:
            raise ValueError(
                "ScenarioFleet lifts consensus couplings only; "
                f"group {group.name!r} declares exchanges "
                f"{sorted(group.exchanges)}")
        self.group = FusedADMM._with_stage_partition(group)
        self.tree = tree.validate(group.ocp.N)
        self.options = options
        self.T = group.ocp.N
        self.n_u = len(group.ocp.control_names)
        self.S = tree.n_scenarios
        self.R = tree.robust_horizon if self.S > 1 else 0
        self._aliases = sorted(group.couplings)
        if active is None:
            active = jnp.ones((group.n_agents,), bool)
        self.active = jnp.asarray(active, bool)
        if self.active.shape != (group.n_agents,):
            raise ValueError(
                f"active mask has shape {self.active.shape}, expected "
                f"({group.n_agents},)")
        if collective_certify not in ("auto", "require", "off"):
            raise ValueError(
                f"collective_certify must be 'auto', 'require' or "
                f"'off', got {collective_certify!r}")
        self.collective_certify = collective_certify
        self.collective_certificate = None
        self.collective_schedule_digest = None
        if memory_certify not in ("auto", "require", "off"):
            raise ValueError(
                f"memory_certify must be 'auto', 'require' or 'off', "
                f"got {memory_certify!r}")
        self.memory_certify = memory_certify
        self.memory_certificate = None
        self.memory_digest = None
        if dispatch_certify not in ("auto", "require", "off"):
            raise ValueError(
                f"dispatch_certify must be 'auto', 'require' or 'off', "
                f"got {dispatch_certify!r}")
        self.dispatch_certify = dispatch_certify
        self.dispatch_certificate = None
        self.dispatch_digest = None
        if precision_certify not in ("auto", "require", "off"):
            raise ValueError(
                f"precision_certify must be 'auto', 'require' or "
                f"'off', got {precision_certify!r}")
        self.precision_certify = precision_certify
        self.precision_certificate = None
        self.precision_digest = None
        self.watchdog_timeout_s = (None if watchdog_timeout_s is None
                                   else float(watchdog_timeout_s))
        #: True once a round blew the collective-watchdog budget — the
        #: supervisor resets it when it decides the mesh may serve again
        self.mesh_condemned = False
        #: the bounded per-device probe a condemned round leaves behind
        self.shard_report = None
        self._watchdog_reader = None
        self.mesh = mesh
        #: learned warm-start bundle + most recent cold start's per-lane
        #: provenance ((n_agents, S) int32 of INIT_POINT_SOURCES codes)
        self.warmstart = None
        self.warmstart_enabled = True
        self.last_init_sources = None
        self._warmstart_init = None
        if warmstart is not None:
            self._install_warmstart(warmstart)
        self._membership, self._counts = self._build_membership()
        self._compile_step()
        if telemetry.enabled():
            telemetry.gauge(
                "scenario_count",
                "disturbance scenarios batched per agent in the "
                "scenario fleet").set(float(self.S))

    def _install_warmstart(self, warmstart) -> None:
        """Resolve a warm-start document/bundle against the fleet's
        group structure; drift (fingerprint mismatch) refuses."""
        from agentlib_mpc_tpu.ml import warmstart as ws_mod
        from agentlib_mpc_tpu.serving.fingerprint import tenant_fingerprint

        bundle = warmstart
        if not isinstance(bundle, ws_mod.WarmstartBundle):
            bundle = ws_mod.build_warmstart(
                bundle, fingerprint=warmstart.fingerprint)
        if tenant_fingerprint(self.group.ocp).digest != bundle.fingerprint:
            raise ws_mod.WarmstartDriftError(
                f"warm-start artifact (fingerprint {bundle.fingerprint}) "
                f"does not match scenario group {self.group.name!r}")
        checked = ws_mod.build_warmstart(bundle.model, ocp=self.group.ocp)
        # agents x scenarios, like init_state's double-vmapped guess
        self._warmstart_init = jax.jit(jax.vmap(jax.vmap(
            ws_mod.make_gated_init(self.group.ocp, checked),
            in_axes=(None, None, 0)), in_axes=(None, None, 0)))
        self.warmstart = bundle

    # -- static layout --------------------------------------------------------

    def _build_membership(self):
        """(S, R, G) one-hot node membership + (R, G) static group
        sizes. The membership rides the step as a TRACED input sharded
        over the scenario axis (a shard-local body only sees its own
        scenario rows); the counts are global constants."""
        R, S = self.R, self.S
        if R == 0:
            return (jnp.zeros((S, 0, 1)), np.ones((0, 1)))
        G = max(len(self.tree.groups_at(t)) for t in range(R))
        M = np.zeros((S, R, G))
        counts = np.ones((R, G))
        for t in range(R):
            node_ids = sorted(set(self.tree.node_of[t]))
            slot_of = {n: g for g, n in enumerate(node_ids)}
            for s, node in enumerate(self.tree.node_of[t]):
                M[s, t, slot_of[node]] = 1.0
            for g, grp in enumerate(self.tree.groups_at(t)):
                counts[t, g] = float(len(grp))
        return jnp.asarray(M), counts

    # -- state ----------------------------------------------------------------

    def init_state(self, theta_batch,
                   warmstart_enabled: "bool | None" = None) -> ScenarioState:
        """Fresh state for an (n_agents, S)-leading theta batch.

        With a learned warm-start installed, every (agent, scenario)
        lane's primal/dual/``lam`` cold start comes from the in-graph
        gated prediction (rejected lanes keep the plain start);
        ``warmstart_enabled`` overrides ``self.warmstart_enabled`` for
        this call as traced data (no retrace on flip)."""
        g = self.group
        zbar = {a: jnp.zeros((self.S, self.T)) for a in self._aliases}
        lam = {a: jnp.zeros((g.n_agents, self.S, self.T))
               for a in self._aliases}
        nu = jnp.zeros((g.n_agents, self.S, self.R, self.n_u))
        fdtype = jnp.zeros(()).dtype
        w = jax.vmap(jax.vmap(g.ocp.initial_guess))(theta_batch)
        y = jnp.zeros((g.n_agents, self.S, g.ocp.n_g))
        z = jnp.full((g.n_agents, self.S, g.ocp.n_h), 0.1, dtype=fdtype)
        if self._warmstart_init is not None:
            from agentlib_mpc_tpu.ml import warmstart as ws_mod

            enabled = (self.warmstart_enabled if warmstart_enabled is None
                       else bool(warmstart_enabled))
            w_p, y_p, z_p, lam_p, src = self._warmstart_init(
                self.warmstart.params, enabled, theta_batch)
            w = w_p.astype(w.dtype)
            y = y_p.astype(fdtype)
            z = z_p.astype(fdtype)
            aliases = self.warmstart.aliases
            if aliases and lam_p.shape[-1]:
                rows = lam_p.reshape(
                    g.n_agents, self.S, len(aliases), self.T)
                for ai, alias in enumerate(aliases):
                    if alias in lam:
                        lam[alias] = rows[:, :, ai, :].astype(fdtype)
            self.last_init_sources = src
            ws_mod.record_init_sources(
                [src], scope="scenario_fleet", names=[g.name])
        else:
            self.last_init_sources = None
        return ScenarioState(zbar=zbar, lam=lam, nu=nu,
                             na_target=jnp.zeros_like(nu),
                             w=w, y=y, z=z)

    def shift_state(self, state: ScenarioState) -> ScenarioState:
        """Shift-by-one warm start between control steps (trajectory
        leaves only; multipliers and primal iterates carry over)."""
        sh = lambda a: admm_ops.shift_one(a, self.T)
        return state._replace(
            zbar={k: sh(v) for k, v in state.zbar.items()},
            lam={k: sh(v) for k, v in state.lam.items()})

    # -- the fused iteration loop ---------------------------------------------

    def _build_step(self, ax_a=None, ax_s=None):
        g = self.group
        ocp = g.ocp
        opts = self.options
        aliases = self._aliases
        R, n_u = self.R, self.n_u
        cols = {a: g.control_index(n)
                for a, n in sorted(g.couplings.items())}
        counts = jnp.asarray(self._counts)

        def f_aug(w_flat, theta):
            # scenario weight rides theta (probabilities are data);
            # coupling penalties are dt-integrated like the base cost
            # (the FusedADMM convention)
            ocp_theta, weight, aug, na = theta
            val = weight * ocp.nlp.f(w_flat, ocp_theta)
            u = ocp.unflatten(w_flat)["u"]
            for k, alias in enumerate(aliases):
                zbar_s, lam_s, rho = aug[k]
                val = val + ocp.dt * consensus_penalty(
                    u[:, cols[alias]], zbar_s, lam_s, rho)
            if na is not None:
                target, nu_s, rho_na = na
                val = val + ocp.dt * consensus_penalty(
                    u[:R], target, nu_s, rho_na)
            return val

        nlp_aug = NLPFunctions(
            f=f_aug,
            g=lambda w, th: ocp.nlp.g(w, th[0]),
            h=lambda w, th: ocp.nlp.h(w, th[0]),
        )

        # stage-sparse derivative plan on the AUGMENTED nlp (the tree
        # branches share it — tree_plan_from_certificate's one-proof
        # contract), attached through the shared gate+certify seam
        from agentlib_mpc_tpu.ops import stagejac

        theta0 = ocp.default_params()
        aug0 = tuple((jnp.zeros((self.T,)), jnp.zeros((self.T,)),
                      jnp.asarray(1.0)) for _ in aliases)
        na0 = (jnp.zeros((R, n_u)), jnp.zeros((R, n_u)),
               jnp.asarray(1.0)) if R else None
        n_w = int(ocp.initial_guess(theta0).shape[0])
        part = getattr(ocp, "stage_partition", None)
        solver_opts = stagejac.attach_plan_if_worthwhile(
            g.solver_options, part, nlp_aug,
            (theta0, jnp.asarray(1.0), aug0, na0), n_w,
            label=f"scenario group {g.name!r}")

        def local_solves(state, theta_batch, scen_weight, mu0, budget,
                         rho_na_t):
            def one(w0, y0, z0, th, wgt, zbars, lams, target, nu_s):
                aug = tuple(
                    (zbars[k], lams[k], jnp.asarray(opts.rho))
                    for k in range(len(aliases)))
                na = (target, nu_s, rho_na_t) if R else None
                lb, ub = ocp.bounds(th)
                res = solve_nlp(nlp_aug, w0, (th, wgt, aug, na), lb, ub,
                                solver_opts, y0=y0, z0=z0, mu0=mu0,
                                max_iter=budget)
                u = ocp.unflatten(res.w)["u"]
                return res.w, res.y, res.z, u, res.stats.success

            # inner vmap: scenarios; outer: agents. zbar is per
            # scenario (replicated over agents), lam per (agent,
            # scenario).
            zbars = tuple(state.zbar[a] for a in aliases)
            lams = tuple(state.lam[a] for a in aliases)
            over_s = jax.vmap(
                one, in_axes=(0, 0, 0, 0, 0, (0,) * len(aliases),
                              (0,) * len(aliases), 0, 0))
            over_as = jax.vmap(
                over_s, in_axes=(0, 0, 0, 0, None,
                                 (None,) * len(aliases),
                                 (0,) * len(aliases), 0, 0))
            return over_as(state.w, state.y, state.z, theta_batch,
                           scen_weight, zbars, lams, state.na_target,
                           state.nu)

        def close_sum(v):
            if ax_s is not None:
                v = jax.lax.psum(v, ax_s)
            return v

        def close_res(res: AdmmResiduals) -> AdmmResiduals:
            """Close per-scenario-shard partial residuals over the
            scenario mesh axis (rss for norms, sum for counts)."""
            if ax_s is None:
                return res
            rss = lambda v: jnp.sqrt(jax.lax.psum(v ** 2, ax_s))
            return AdmmResiduals(
                primal=rss(res.primal), dual=rss(res.dual),
                scale_primal=rss(res.scale_primal),
                scale_dual=rss(res.scale_dual),
                n_primal=jax.lax.psum(res.n_primal, ax_s),
                n_dual=jax.lax.psum(res.n_dual, ax_s))

        def gnorm(arr):
            sq = jnp.sum(arr.reshape(-1) ** 2)
            if ax_a is not None:
                sq = jax.lax.psum(sq, ax_a)
            return jnp.sqrt(close_sum(sq))

        quarantine = bool(opts.quarantine)
        q_reset_after = max(int(opts.quarantine_reset_after), 1)

        def lane_finite(arr):
            """All-finite per (agent, scenario) lane — reduce every
            trailing axis."""
            return jnp.all(jnp.isfinite(arr),
                           axis=tuple(range(2, arr.ndim)))

        def apply_quarantine(state, theta_batch, streak,
                             w_b, y_b, z_b, u_b, active):
            """Quarantine diverged (agent, scenario) branches inside
            the jit — the FusedADMM substitution at branch granularity:
            a non-finite branch solution is replaced by that branch's
            previous iterate via ``jnp.where`` (elementwise only — no
            new collectives, so the certified two-family schedule and
            its psum pins are untouched), branches quarantined
            ``quarantine_reset_after`` iterations in a row restart from
            the sanitized OCP initial guess, and the per-branch
            attribution rides out on ``ScenarioStats.lane_quarantined``
            (the substitution keeps the decoded trajectory finite, so
            without this column a persistently-NaN branch looks healthy
            forever)."""
            bad = ~(lane_finite(w_b) & lane_finite(y_b)
                    & lane_finite(z_b) & lane_finite(u_b))
            u_prev = jax.vmap(jax.vmap(
                lambda w: ocp.unflatten(w)["u"]))(state.w)
            sub2 = bad[:, :, None]
            w_b = jnp.where(sub2, state.w, w_b)
            y_b = jnp.where(sub2, state.y, y_b)
            z_b = jnp.where(sub2, state.z, z_b)
            u_b = jnp.where(bad[:, :, None, None], u_prev, u_b)
            streak = jnp.where(bad, streak + 1, 0)
            resetting = streak >= q_reset_after
            w_init = jax.vmap(jax.vmap(ocp.initial_guess))(theta_batch)
            w_init = jnp.where(jnp.isfinite(w_init), w_init, 0.0)
            w_b = jnp.where(resetting[:, :, None], w_init, w_b)
            y_b = jnp.where(resetting[:, :, None], 0.0, y_b)
            z_b = jnp.where(resetting[:, :, None], 0.1, z_b)
            streak = jnp.where(resetting, 0, streak)
            # last-resort elementwise sanitize: a poisoned carry must
            # never write NaN into the group projection — an unmasked
            # NaN mean bakes NaN into every member branch's multiplier
            w_b = jnp.where(jnp.isfinite(w_b), w_b, 0.0)
            y_b = jnp.where(jnp.isfinite(y_b), y_b, 0.0)
            z_b = jnp.where(jnp.isfinite(z_b), z_b, 0.1)
            u_b = jnp.where(jnp.isfinite(u_b), u_b, 0.0)
            q_bad = bad & active[:, None]
            return w_b, y_b, z_b, u_b, streak, q_bad

        def step_fn(state: ScenarioState, theta_batch, active,
                    membership, scen_weight):
            max_it = opts.max_iterations
            act4 = active[:, None, None, None].astype(state.nu.dtype)

            def na_project(u_na):
                """Group-mean projection of the robust-horizon controls
                across the scenario axis: the ONE scenarios-psum of the
                non-anticipativity coupling."""
                with phase_scope("non_anticipativity"):
                    partial = jnp.einsum(
                        "astu,stg->atgu", u_na, membership,
                        precision=jax.lax.Precision.HIGHEST)
                    sums = partial
                    if ax_s is not None:
                        with phase_scope("collectives"):
                            sums = jax.lax.psum(sums, ax_s)
                    means = sums / counts[None, :, :, None]
                    return jnp.einsum(
                        "stg,atgu->astu", membership, means,
                        precision=jax.lax.Precision.HIGHEST)

            def iteration(carry):
                (state, it, _res, prim_h, dual_h, done, ok_hist,
                 na_last, q_streak, q_lane) = carry
                is_cold = it == 0
                cold = g.solver_options
                mu0 = jnp.where(is_cold, cold.mu_init, opts.warm_mu)
                budget = jnp.where(is_cold, cold.max_iter,
                                   opts.warm_budget)
                # iteration 0 has no projection target yet — the NA
                # penalty ramps in from the first computed group mean
                rho_na_t = jnp.where(is_cold, 0.0,
                                     jnp.asarray(opts.rho_na))
                w_b, y_b, z_b, u_b, ok_b = local_solves(
                    state, theta_batch, scen_weight, mu0, budget,
                    rho_na_t)
                if quarantine:
                    w_b, y_b, z_b, u_b, q_streak, q_bad = \
                        apply_quarantine(state, theta_batch, q_streak,
                                         w_b, y_b, z_b, u_b, active)
                    q_lane = q_lane + q_bad.astype(jnp.int32)
                n_failed = jnp.sum(
                    ~(ok_b | ~active[:, None]), dtype=jnp.int32)
                if ax_a is not None:
                    with phase_scope("collectives"):
                        n_failed = jax.lax.psum(n_failed, ax_a)
                n_failed = close_sum(n_failed)
                ok_all = n_failed == 0

                residuals = []
                zbar_new = dict(state.zbar)
                lam_new = dict(state.lam)
                for alias in aliases:
                    locals_ = u_b[:, :, :, cols[alias]]  # (n_a, S, T)
                    cstate = admm_ops.ConsensusState(
                        zbar=state.zbar[alias], lam=state.lam[alias],
                        rho=jnp.asarray(opts.rho))
                    cnew, res = admm_ops.consensus_update(
                        locals_, cstate, active=active, axis_name=ax_a)
                    residuals.append(close_res(res))
                    zbar_new[alias] = cnew.zbar
                    lam_new[alias] = cnew.lam

                if R:
                    with phase_scope("non_anticipativity"):
                        u_na = u_b[:, :, :R, :]        # (n_a, S, R, n_u)
                        target = na_project(u_na)
                        prim_per = (target - u_na) * act4
                        nu_new = state.nu - opts.rho_na * prim_per
                        na_res = AdmmResiduals(
                            primal=gnorm(prim_per),
                            dual=gnorm(opts.rho_na
                                       * (target - state.na_target)
                                       * act4),
                            scale_primal=jnp.maximum(
                                gnorm(u_na * act4),
                                gnorm(target * act4)),
                            scale_dual=gnorm(nu_new * act4),
                            # constraint elements: active agents x ALL
                            # scenarios (static) x coupled coordinates —
                            # no scenario psum needed for a static count
                            n_primal=_active_count(active, ax_a)
                            * float(self.S * R * n_u),
                            n_dual=_active_count(active, ax_a)
                            * float(self.S * R * n_u))
                    residuals.append(na_res)
                    na_last = na_res.primal
                else:
                    target, nu_new = state.na_target, state.nu

                res_all = admm_ops.combine_residuals(*residuals) \
                    if residuals else AdmmResiduals(
                        *([jnp.asarray(0.0)] * 6))
                is_conv = admm_ops.converged(
                    res_all, abs_tol=opts.abs_tol, rel_tol=opts.rel_tol,
                    use_relative=opts.use_relative_tolerances,
                    primal_tol=opts.primal_tol, dual_tol=opts.dual_tol)
                prim_h = prim_h.at[it].set(res_all.primal)
                dual_h = dual_h.at[it].set(res_all.dual)
                state = state._replace(
                    zbar=zbar_new, lam=lam_new, nu=nu_new,
                    na_target=target, w=w_b, y=y_b, z=z_b)
                return (state, it + 1, res_all, prim_h, dual_h,
                        is_conv, ok_hist & ok_all, na_last, q_streak,
                        q_lane)

            def cond(carry):
                done, it = carry[5], carry[1]
                return (~done) & (it < max_it)

            nan_hist = jnp.full((max_it,), jnp.nan)
            init_res = AdmmResiduals(*([jnp.asarray(jnp.inf)] * 2),
                                     *([jnp.asarray(0.0)] * 4))
            q_shape = (state.w.shape[0], state.w.shape[1])
            carry = (state, jnp.asarray(0), init_res, nan_hist,
                     jnp.full((max_it,), jnp.nan), jnp.asarray(False),
                     jnp.asarray(True), jnp.asarray(0.0),
                     jnp.zeros(q_shape, jnp.int32),
                     jnp.zeros(q_shape, jnp.int32))
            (state, it, _res, prim_h, dual_h, done, ok_hist,
             na_last, _q_streak, q_lane) = jax.lax.while_loop(
                cond, iteration, carry)

            stats = ScenarioStats(
                iterations=it, primal_residuals=prim_h,
                dual_residuals=dual_h, converged=done,
                local_solves_ok=ok_hist, na_spread=na_last,
                lane_quarantined=q_lane if quarantine else None)
            trajs = jax.vmap(jax.vmap(ocp.trajectories))(state.w,
                                                         theta_batch)
            return state, trajs, stats

        return step_fn

    def _compile_step(self) -> None:
        self._scen_weight = jnp.asarray(
            self.tree.probabilities) * float(self.S)
        if self.mesh is None:
            step_fn = self._build_step()
            self._step_fn = step_fn
            self._step = jax.jit(step_fn)
            if self._memory_certify_wanted():
                self._certify_memory(None)
            if self._dispatch_certify_wanted():
                self._certify_dispatch(None)
            if self._precision_certify_wanted():
                self._certify_precision(None)
            return

        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        names = tuple(mesh.axis_names)
        if names not in (("agents",), ("agents", "scenarios")):
            raise ValueError(
                f"ScenarioFleet meshes are 1-D ('agents',) or 2-D "
                f"('agents', 'scenarios'); got {names} "
                f"(use multihost.scenario_mesh())")
        ax_a = "agents"
        ax_s = "scenarios" if len(names) == 2 else None
        n_ash = int(mesh.shape["agents"])
        n_ssh = int(mesh.shape["scenarios"]) if ax_s else 1
        if self.group.n_agents % n_ash:
            raise ValueError(
                f"{self.group.n_agents} agents do not divide the "
                f"{n_ash}-shard agent axis — pad the group first "
                f"(parallel.fused_admm.pad_group_to_devices)")
        if self.S % n_ssh:
            raise ValueError(
                f"{self.S} scenarios do not divide the {n_ssh}-shard "
                f"scenario axis — pad the tree (or pick a divisible "
                f"scenario count)")

        sh_a = P(ax_a)
        sh_as = P(ax_a, ax_s) if ax_s else P(ax_a)
        sh_s = P(ax_s) if ax_s else P()
        state_spec = ScenarioState(
            zbar={a: sh_s for a in self._aliases},
            lam={a: sh_as for a in self._aliases},
            nu=sh_as, na_target=sh_as, w=sh_as, y=sh_as, z=sh_as)
        # lane_quarantined is the ONE sharded stats out-spec: per-branch
        # attribution keeps global (agents, scenarios) rows (with
        # quarantine off the body returns None there, which a P() spec
        # happily covers)
        stats_spec = ScenarioStats(
            *([P()] * 6),
            lane_quarantined=(sh_as if self.options.quarantine
                              else P()))
        step_fn = self._build_step(ax_a=ax_a, ax_s=ax_s)
        # check_rep=False for the same reason FusedADMM sets it: the
        # psum'ed loop outputs are replicated by construction, which
        # the checker cannot see through while_loop carries — the
        # build-time certificate below is the proof that claim rests on
        sharded = shard_map(
            step_fn, mesh=mesh,
            in_specs=(state_spec, sh_as, sh_a, sh_s, sh_s),
            out_specs=(state_spec, sh_as, stats_spec),
            check_rep=False)
        self._step_fn = sharded
        self._step = jax.jit(sharded)
        if self.collective_certify != "off":
            self._certify(sharded, names)
        else:
            if self._memory_certify_wanted():
                self._certify_memory(None)
            if self._dispatch_certify_wanted():
                self._certify_dispatch(None)
            if self._precision_certify_wanted():
                self._certify_precision(None)

    def _certify(self, sharded, axis_names: tuple) -> None:
        """Trace the sharded step on shape templates and certify the
        collective schedule (the FusedADMM build-time pattern): exactly
        one psum family per mesh axis per ADMM iteration, proved by the
        per-axis replication lattice before the program can ever wedge
        a pod behind a divergent collective."""
        from agentlib_mpc_tpu.lint.jaxpr.collectives import (
            certify_collectives,
        )

        closed = jax.make_jaxpr(sharded)(*self._step_templates())
        cert = certify_collectives(closed, allowed_axes=axis_names)
        if self._memory_certify_wanted():
            self._certify_memory(closed)
        if self._dispatch_certify_wanted():
            self._certify_dispatch(closed)
        if self._precision_certify_wanted():
            self._certify_precision(closed)
        self.collective_certificate = cert
        self.collective_schedule_digest = cert.schedule_digest
        if cert.status == "refuted":
            detail = "\n  ".join(cert.refutations)
            msg = (f"scenario fleet's collective schedule REFUTED — "
                   f"dispatching it on a multi-process mesh risks a "
                   f"silent cross-host hang:\n  {detail}")
            if self.collective_certify == "require" or \
                    jax.process_count() > 1:
                raise ValueError(msg)
            logger.warning("%s\n(single-host mesh: proceeding)", msg)
        elif cert.status == "unknown":
            if self.collective_certify == "require":
                raise ValueError(
                    f"scenario fleet's collective schedule is "
                    f"UNPROVABLE ({cert.describe()}) under "
                    f"collective_certify='require'")
            logger.info("scenario schedule not provable (%s)",
                        cert.describe())
        else:
            logger.info("scenario schedule proved: %s (digest %s)",
                        cert.describe(), cert.schedule_digest)

    def _step_templates(self) -> tuple:
        """(state, theta, mask, membership, weight) shape templates of
        the compiled step — shared by the collective and memory
        certifier traces and the gate's XLA cross-check."""
        g = self.group

        def sds(leaf):
            arr = jnp.asarray(leaf)
            return jax.ShapeDtypeStruct(
                (g.n_agents, self.S) + arr.shape, arr.dtype)

        theta_tmpl = jax.tree.map(sds, g.ocp.default_params())
        state_tmpl = jax.eval_shape(self.init_state, theta_tmpl)
        mask_tmpl = jax.ShapeDtypeStruct((g.n_agents,), jnp.bool_)
        memb_tmpl = jax.ShapeDtypeStruct(
            tuple(self._membership.shape), self._membership.dtype)
        wgt_tmpl = jax.ShapeDtypeStruct((self.S,),
                                        self._scen_weight.dtype)
        return state_tmpl, theta_tmpl, mask_tmpl, memb_tmpl, wgt_tmpl

    def _memory_certify_wanted(self) -> bool:
        """The :class:`FusedADMM` policy verbatim: ``"require"``
        always, ``"auto"`` when the trace is already paid (mesh
        engines) or the backend reports a capacity, ``"off"`` never."""
        if self.memory_certify == "off":
            return False
        if self.memory_certify == "require":
            return True
        if self.mesh is not None and self.collective_certify != "off":
            return True
        from agentlib_mpc_tpu.lint.jaxpr.memory import device_hbm_bytes

        return device_hbm_bytes() is not None

    def _certify_memory(self, closed) -> None:
        """Certify the robust round's per-device peak bytes (ISSUE 13)
        and enforce the capacity policy — the scenario axis multiplies
        every lane buffer by S, which is exactly what this prices."""
        from agentlib_mpc_tpu.lint.jaxpr.memory import (
            MemoryBudgetExceeded,
            certify_memory,
            device_hbm_bytes,
        )

        if closed is None:
            closed = jax.make_jaxpr(self._step_fn)(
                *self._step_templates())
        cert = certify_memory(closed)
        self.memory_certificate = cert
        self.memory_digest = cert.memory_digest
        if telemetry.enabled():
            telemetry.gauge(
                "memory_certified_peak_bytes",
                "statically certified per-device peak bytes-resident "
                "of the fused step (lint/jaxpr/memory.py, set at "
                "engine build)").set(
                float(cert.peak_bytes),
                fleet=f"scenario:{self.group.name}")
        if cert.status != "proved":
            if self.memory_certify == "require":
                raise MemoryBudgetExceeded(
                    f"scenario round's memory footprint is not "
                    f"provable ({cert.describe()}) and memory_certify="
                    f"'require' was set")
            logger.info("scenario memory footprint not provable (%s)",
                        cert.describe())
            if cert.status == "unknown":
                return
        hbm = device_hbm_bytes()
        if hbm is not None and cert.peak_bytes > hbm:
            raise MemoryBudgetExceeded(
                f"scenario round's certified per-device peak "
                f"({cert.describe()}) exceeds the backend device's "
                f"reported capacity ({hbm} B) — dispatching would OOM "
                f"the mesh. Fewer scenario branches per device "
                f"(lint.jaxpr.memory.plan_capacity prices the "
                f"scenario marginal), or memory_certify='off' to "
                f"override")
        logger.info("scenario memory certificate: %s (digest %s)",
                    cert.describe(), cert.memory_digest)

    def _dispatch_certify_wanted(self) -> bool:
        """The :class:`FusedADMM` policy verbatim (ISSUE 18):
        ``"require"`` always; ``"auto"`` whenever the build already
        pays a trace; ``"off"`` never."""
        if self.dispatch_certify == "off":
            return False
        if self.dispatch_certify == "require":
            return True
        if self.mesh is not None and self.collective_certify != "off":
            return True
        return self._memory_certify_wanted()

    def _certify_dispatch(self, closed) -> None:
        """Certify the robust round's dispatch schedule (ISSUE 18) and
        enforce the host-sync policy — the FusedADMM pattern."""
        from agentlib_mpc_tpu.lint.jaxpr.dispatch import certify_dispatch

        if closed is None:
            closed = jax.make_jaxpr(self._step_fn)(
                *self._step_templates())
        cert = certify_dispatch(closed)
        self.dispatch_certificate = cert
        self.dispatch_digest = cert.dispatch_digest
        if cert.status == "refuted":
            detail = "\n  ".join(cert.refutations)
            msg = (f"scenario round's dispatch schedule REFUTED — the "
                   f"warm step is not one device program:\n  {detail}")
            if self.dispatch_certify == "require" or \
                    jax.process_count() > 1:
                raise ValueError(msg)
            logger.warning("%s\n(single-host: proceeding)", msg)
        elif cert.status == "unknown":
            if self.dispatch_certify == "require":
                raise ValueError(
                    f"scenario round's dispatch schedule is UNPROVABLE "
                    f"({cert.describe()}) under dispatch_certify="
                    f"'require'")
            logger.info("scenario dispatch schedule not provable (%s)",
                        cert.describe())
        else:
            logger.info("scenario dispatch schedule proved: %s "
                        "(digest %s)", cert.describe(),
                        cert.dispatch_digest)

    def _precision_certify_wanted(self) -> bool:
        """The :class:`FusedADMM` policy verbatim (ISSUE 20):
        ``"require"`` always; the group demanding
        ``SolverOptions.precision="require"`` always; ``"auto"`` when
        the group actually resolves to the mixed path on this backend;
        ``"off"`` never."""
        if self.precision_certify == "off":
            return False
        if self.precision_certify == "require":
            return True
        from agentlib_mpc_tpu.ops.solver import (
            SolverOptions,
            _resolve_precision,
        )

        opts = []
        for o in (self.group.solver_options,
                  self.group.warm_solver_options):
            opts.append(o if o is not None else SolverOptions())
        if any(getattr(o, "precision", None) == "require"
               for o in opts):
            return True
        return any(_resolve_precision(o) == "mixed" for o in opts)

    def _certify_precision(self, closed) -> None:
        """Certify the robust round's per-phase error growth (ISSUE
        20) and enforce the proof policy — the FusedADMM pattern: a
        refuted or unprovable certificate raises when a proof was
        demanded (``precision_certify="require"`` or the group's
        ``SolverOptions.precision="require"``), warns loudly
        otherwise."""
        from agentlib_mpc_tpu.lint.jaxpr.precision import certify_precision
        from agentlib_mpc_tpu.ops.solver import SolverOptions

        if closed is None:
            closed = jax.make_jaxpr(self._step_fn)(
                *self._step_templates())
        cert = certify_precision(closed)
        self.precision_certificate = cert
        self.precision_digest = cert.precision_digest
        hard = self.precision_certify == "require" or any(
            getattr(o if o is not None else SolverOptions(),
                    "precision", None) == "require"
            for o in (self.group.solver_options,
                      self.group.warm_solver_options))
        if cert.status == "refuted":
            detail = "\n  ".join(cert.refutations)
            msg = (f"scenario round's mixed-precision routing REFUTED "
                   f"— a narrow phase cannot carry its certified "
                   f"error budget:\n  {detail}")
            if hard:
                raise ValueError(msg)
            logger.warning(
                "%s\n(proceeding — 'mixed' groups run the narrow "
                "phases UNCERTIFIED)", msg)
        elif cert.status != "proved":
            if hard:
                raise ValueError(
                    f"scenario round's precision certificate is "
                    f"UNPROVABLE ({cert.describe()}) and a proof was "
                    f"required")
            logger.info("scenario precision not provable (%s)",
                        cert.describe())
        else:
            logger.info("scenario precision certificate proved: %s "
                        "(digest %s)", cert.describe(),
                        cert.precision_digest)

    # -- public API -----------------------------------------------------------

    def step(self, state: ScenarioState, theta_batch, active=None):
        """One fused robust round. ``theta_batch``: OCPParams pytree
        with (n_agents, S) leading axes (``scenario.generate`` builds
        it). Returns (new_state, per-(agent, scenario) trajectory
        pytree, :class:`ScenarioStats`)."""
        mask = self.active if active is None else jnp.asarray(active,
                                                              bool)
        args = (state, theta_batch, mask, self._membership,
                self._scen_weight)
        if self.watchdog_timeout_s is not None:
            return self._step_watchdogged(args)
        if not telemetry.enabled():
            return self._step(*args)
        with telemetry.span("scenario.fused_step", group=self.group.name,
                            scenarios=str(self.S)):
            out = self._step(*args)
        self._record_round(out[2])
        return out

    def _step_watchdogged(self, args):
        """One robust round under the collective watchdog: dispatch AND
        sync run on a bounded daemon reader (the :class:`FusedADMM`
        pattern over both mesh axes — a wedged 2-D collective cannot be
        cancelled, only abandoned). On timeout the mesh is condemned, a
        bounded per-device re-probe records which shards of the FULL
        (agents × scenarios) grid answered, and
        :class:`~agentlib_mpc_tpu.parallel.multihost.MeshRoundTimeout`
        carries the report out for the supervisor's axis
        classification."""
        from agentlib_mpc_tpu.parallel.multihost import (
            MESH_PROBE_TIMEOUT_S,
            MeshRoundTimeout,
            probe_mesh_devices,
        )

        if self._watchdog_reader is None:
            from agentlib_mpc_tpu.utils.watchdog import BoundedReader

            self._watchdog_reader = BoundedReader(
                name="scenario-round-reader")

        def dispatch():
            if telemetry.enabled():
                with telemetry.span("scenario.fused_step",
                                    group=self.group.name,
                                    scenarios=str(self.S)):
                    out = self._step(*args)
            else:
                out = self._step(*args)
            jax.block_until_ready(out)
            return out

        kind, value = self._watchdog_reader.run(dispatch,
                                                self.watchdog_timeout_s)
        if kind == "err":
            raise value
        if kind in ("timeout", "saturated"):
            self.mesh_condemned = True
            if telemetry.enabled():
                telemetry.counter(
                    "mesh_watchdog_stalls_total",
                    "mesh-dispatched fused rounds that blew the "
                    "collective-watchdog budget").inc(outcome=kind)
            telemetry.journal_event(
                "watchdog.condemned", scope="scenario", outcome=kind,
                budget_s=self.watchdog_timeout_s,
                groups=[self.group.name], scenarios=int(self.S),
                mesh_shape=(None if self.mesh is None else
                            [int(s) for s in self.mesh.devices.shape]))
            probe = None
            if self.mesh is not None:
                probe = probe_mesh_devices(
                    self.mesh, min(self.watchdog_timeout_s,
                                   MESH_PROBE_TIMEOUT_S))
                self.shard_report = probe
                telemetry.journal_event(
                    "watchdog.probe", scope="scenario",
                    answered=list(probe.answered),
                    dead=list(probe.dead),
                    latency_s={str(k): round(v, 4) for k, v
                               in probe.latency_s.items()})
                logger.error(
                    "scenario round blew the %.1fs collective watchdog; "
                    "2-D mesh condemned — per-device probe: %d/%d "
                    "shards answered (dead: %s)",
                    self.watchdog_timeout_s, len(probe.answered),
                    len(probe.answered) + len(probe.dead),
                    list(probe.dead) or "none")
            else:
                logger.error(
                    "scenario round blew the %.1fs watchdog on a "
                    "mesh-less fleet; no shards to probe",
                    self.watchdog_timeout_s)
            raise MeshRoundTimeout(
                f"scenario round did not complete within the "
                f"{self.watchdog_timeout_s:.1f}s collective-watchdog "
                f"budget" + ("" if kind == "timeout" else
                             " (watchdog reader leak cap reached — the "
                             "mesh is already known-dead)"), probe=probe)
        if telemetry.enabled():
            self._record_round(value[2])
        return value

    def _record_round(self, stats: ScenarioStats) -> None:
        telemetry.gauge(
            "scenario_count",
            "disturbance scenarios batched per agent in the scenario "
            "fleet").set(float(self.S))
        telemetry.histogram(
            "scenario_spread",
            "final non-anticipativity primal residual per fused robust "
            "round (distance of branch controls from their group "
            "projection)").observe(float(stats.na_spread))
        telemetry.counter(
            "scenario_rounds_total",
            "fused scenario-tree robust rounds run").inc(
            group=self.group.name)
        if stats.lane_quarantined is not None:
            n_q = int(np.asarray(stats.lane_quarantined).sum())
            if n_q:
                # per-branch attribution rolled up: total (branch ×
                # iteration) quarantine events this round — the robust
                # tenants' third sickness signal, decodable per branch
                # from the stats row itself
                telemetry.counter(
                    "scenario_quarantined_iters",
                    "quarantined (branch, iteration) events inside "
                    "fused scenario rounds — non-finite branch "
                    "solutions substituted by the previous iterate"
                    ).inc(n_q, group=self.group.name)
        telemetry.record_device_memory()
        return None

    def actuated_u0(self, state: ScenarioState) -> jnp.ndarray:
        """The robust controls to actuate: the non-anticipativity
        projection's first-interval rows, (n_agents, S, n_u) —
        identical across every scenario of a root node group BY
        CONSTRUCTION (one shared row for the common all-scenarios fan;
        one row per group for deeper trees). Falls back to the raw
        per-scenario trajectory heads for an uncoupled tree."""
        if self.R:
            return state.na_target[:, :, 0, :]
        u = jax.vmap(jax.vmap(
            lambda w: self.group.ocp.unflatten(w)["u"]))(state.w)
        return u[:, :, 0, :]

    def shard_args(self, mesh, state: ScenarioState, theta_batch):
        """Place the (agents, scenarios)-batched leaves on ``mesh``
        (sharded over both axes; per-scenario means over scenarios
        only). The scenario sibling of ``FusedADMM.shard_args`` —
        shapes must already divide the mesh."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        names = tuple(mesh.axis_names)
        ax_s = "scenarios" if "scenarios" in names else None
        sh_as = NamedSharding(mesh, P("agents", ax_s))
        sh_s = NamedSharding(mesh, P(ax_s))
        put = lambda leaf, sh: jax.device_put(leaf, sh)
        state = state._replace(
            zbar={a: put(v, sh_s) for a, v in state.zbar.items()},
            lam={a: put(v, sh_as) for a, v in state.lam.items()},
            nu=put(state.nu, sh_as),
            na_target=put(state.na_target, sh_as),
            w=put(state.w, sh_as), y=put(state.y, sh_as),
            z=put(state.z, sh_as))
        theta_batch = jax.tree.map(lambda l: put(l, sh_as), theta_batch)
        return state, theta_batch


def _active_count(active, ax_a):
    n = jnp.sum(active.astype(jnp.float32))
    if ax_a is not None:
        n = jax.lax.psum(n, ax_a)
    return n


def pad_scenarios(tree: ScenarioTree, theta_batch, n_shards: int):
    """Pad the scenario axis to a multiple of the mesh's scenario
    shards: padded branches replicate the LAST scenario's data with
    probability 0 (dead weight in the expectation) and join no
    non-anticipativity group beyond their clone's — the scenario-axis
    sibling of
    :func:`~agentlib_mpc_tpu.parallel.fused_admm.pad_group_to_devices`.
    Returns ``(tree, theta_batch)`` grown to the padded count."""
    S = tree.n_scenarios
    n_pad = (-S) % n_shards
    if n_pad == 0:
        return tree, theta_batch
    branch_bytes = sum(
        jnp.asarray(leaf).nbytes
        // max(int(jnp.asarray(leaf).shape[1])
               if jnp.asarray(leaf).ndim > 1 else 1, 1)
        for leaf in jax.tree.leaves(theta_batch))
    logger.warning(
        "scenario tree: padding %d → %d branches for the %d-shard "
        "scenario axis (%.1f%% compute overhead, ≈%.2f MiB projected "
        "per-scenario-shard byte overhead from the padded parameter "
        "branches — "
        "the built fleet's memory certificate prices the exact total: "
        "ScenarioFleet(memory_certify=...))",
        S, S + n_pad, n_shards, 100.0 * n_pad / max(S, 1),
        n_pad * branch_bytes / n_shards / 2**20)
    node_of = tuple(
        nodes + tuple(1_000_000 + i for i in range(n_pad))
        for nodes in tree.node_of)
    probs = tuple(tree.probabilities) + (0.0,) * n_pad
    padded_tree = ScenarioTree(
        n_scenarios=S + n_pad, node_of=node_of, probabilities=probs)
    theta_batch = jax.tree.map(
        lambda leaf: jnp.concatenate(
            [leaf, jnp.repeat(leaf[:, -1:], n_pad, axis=1)], axis=1),
        theta_batch)
    return padded_tree, theta_batch
