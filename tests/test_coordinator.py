"""Coordinated ADMM: coordinator + two employees + plant simulator.

Mirrors the reference's coordinator example family
(``examples/admm/admm_example_coordinator.py``): an `admm_coordinator`
module drives `admm_coordinated` participants through the registration /
start-iteration / optimization wire protocol; convergence by Boyd residuals.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from agentlib_mpc_tpu.models.zoo import CooledRoom, Cooler
from agentlib_mpc_tpu.modules.coordinator import AgentStatus
from agentlib_mpc_tpu.runtime.mas import LocalMAS
import agentlib_mpc_tpu.modules  # noqa: F401

TIME_STEP = 300.0
HORIZON = 8

COORDINATOR = {
    "id": "Coordinator",
    "modules": [
        {"module_id": "com", "type": "local_broadcast"},
        {
            "module_id": "coordinator",
            "type": "admm_coordinator",
            "time_step": TIME_STEP,
            "prediction_horizon": HORIZON,
            "admm_iter_max": 12,
            "penalty_factor": 10.0,
            "abs_tol": 1e-4,
            "rel_tol": 1e-3,
            "penalty_change_threshold": 10.0,
        },
    ],
}


def _employee(aid, model_cls, couplings, controls, extra):
    return {
        "id": aid,
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {
                "module_id": "admm",
                "type": "admm_coordinated",
                "coordinator": "Coordinator",
                "registration_interval": 30.0,
                "optimization_backend": {
                    "type": "jax_admm",
                    "model": {"class": model_cls},
                    "discretization_options": {
                        "collocation_order": 2,
                        "collocation_method": "legendre",
                    },
                    "solver": {"max_iter": 40},
                },
                "time_step": TIME_STEP,
                "prediction_horizon": HORIZON,
                "couplings": couplings,
                "controls": controls,
                **extra,
            },
        ],
    }


ROOM = _employee(
    "CooledRoom", CooledRoom,
    couplings=[{"name": "mDot", "alias": "mDotCoolAir", "value": 0.02,
                "ub": 0.05, "lb": 0.0}],
    controls=[],
    extra={
        "inputs": [
            {"name": "load", "value": 150},
            {"name": "T_in", "value": 290.15},
            {"name": "T_upper", "value": 295.15},
        ],
        "states": [
            {"name": "T", "value": 298.16, "ub": 303.15, "lb": 288.15,
             "alias": "T", "source": "Simulation"},
        ],
        "parameters": [{"name": "s_T", "value": 1.0}],
    },
)

COOLER = _employee(
    "Cooler", Cooler,
    couplings=[{"name": "mDot_out", "alias": "mDotCoolAir", "value": 0.02}],
    controls=[{"name": "mDot", "value": 0.02, "ub": 0.05, "lb": 0.0}],
    extra={"parameters": [{"name": "r_mDot", "value": 1.0}]},
)

SIM = {
    "id": "Simulation",
    "modules": [
        {"module_id": "com", "type": "local_broadcast"},
        {
            "module_id": "simulator",
            "type": "simulator",
            "model": {"class": CooledRoom,
                      "states": [{"name": "T", "value": 298.16}]},
            "t_sample": 60,
            "outputs": [{"name": "T_out", "value": 298.16, "alias": "T"}],
            "inputs": [{"name": "mDot", "value": 0.02, "alias": "mDot"}],
        },
    ],
}


@pytest.fixture(scope="module")
def mas():
    mas = LocalMAS([COORDINATOR, ROOM, COOLER, SIM], env={"rt": False})
    mas.run(until=1500)
    return mas


def test_registration(mas):
    coord = mas.agents["Coordinator"].get_module("coordinator")
    assert len(coord.agent_dict) == 2
    assert all(e.status in (AgentStatus.standby, AgentStatus.ready)
               for e in coord.agent_dict.values())
    assert "mDotCoolAir" in coord._coupling_variables


def test_residuals_decrease(mas):
    coord = mas.agents["Coordinator"].get_module("coordinator")
    stats = coord.results()
    assert stats is not None and len(stats) >= 3
    first_round = stats.loc[stats.index.get_level_values("time")[0]]
    prim = first_round["primal_residual"].to_numpy()
    assert prim[-1] < prim[0], "primal residual should decrease"


def test_room_cools(mas):
    sim = mas.get_results()["Simulation"]["simulator"]
    temps = np.asarray(
        sim[("variable", "T")] if ("variable", "T") in sim else sim["T"],
        dtype=float)
    assert temps[0] > temps[-1]


def test_couplings_agree(mas):
    coord = mas.agents["Coordinator"].get_module("coordinator")
    var = coord._coupling_variables["mDotCoolAir"]
    trajs = list(var.local_trajectories.values())
    assert len(trajs) == 2
    assert np.max(np.abs(trajs[0] - trajs[1])) < 5e-3


def test_midrun_join_new_agent_handshake(mas):
    """A never-seen agent broadcasting a registration mid-run enters the
    two-phase handshake: pending entry + parameter reply, then full
    registration on the guess reply (reference
    ``admm_coordinator.py:596-654``)."""
    from agentlib_mpc_tpu.modules.coordinator import (
        AgentStatus as AS,
    )
    from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source

    coord = mas.agents["Coordinator"].get_module("coordinator")
    src = Source(agent_id="LateZone", module_id="admm")
    n_before = len(coord.agent_dict)
    hello = AgentVariable(name="admm_register_a2c",
                          alias="admm_register_a2c",
                          value=None, source=src)
    coord.registration_callback(hello)
    assert len(coord.agent_dict) == n_before + 1
    assert coord.agent_dict[src].status is AS.pending
    # reply with initial guesses completes the registration
    guesses = AgentVariable(
        name="admm_register_a2c", alias="admm_register_a2c",
        value={"local_trajectory": {"mDotCoolAir": [0.02] * HORIZON},
               "local_exchange_trajectory": {}},
        source=src)
    coord.registration_callback(guesses)
    assert coord.agent_dict[src].status is AS.standby
    assert src in coord._coupling_variables["mDotCoolAir"].local_trajectories
    # cleanup so other fixture-sharing tests see the original fleet
    del coord.agent_dict[src]
    coord._coupling_variables["mDotCoolAir"].local_trajectories.pop(src)
    coord._coupling_variables["mDotCoolAir"].multipliers.pop(src, None)


def test_deregister_slow_agent_midround(mas, caplog):
    """Busy agents that never reply are de-registered for the round
    (reference ``coordinator.py:232-265``)."""
    import logging

    coord = mas.agents["Coordinator"].get_module("coordinator")
    entry = next(iter(coord.agent_dict.values()))
    old_status = entry.status
    entry.status = AgentStatus.busy
    try:
        with caplog.at_level(logging.INFO):
            coord._deregister_slow()
        assert entry.status is AgentStatus.standby
        assert any("de-registered slow agent" in r.message
                   for r in caplog.records)
    finally:
        entry.status = old_status


def test_wait_for_ready_nonblocking_degrades(mas):
    """Non-blocking wait (fast simulation) immediately de-registers
    non-responders instead of deadlocking."""
    coord = mas.agents["Coordinator"].get_module("coordinator")
    entry = next(iter(coord.agent_dict.values()))
    old_status = entry.status
    entry.status = AgentStatus.busy
    try:
        coord._wait_for_ready(block=False)
        assert entry.status is AgentStatus.standby
    finally:
        entry.status = old_status


def test_wait_for_ready_aborts_on_stop(mas):
    """A shutdown request unblocks a coordinator waiting on agents."""
    coord = mas.agents["Coordinator"].get_module("coordinator")
    entry = next(iter(coord.agent_dict.values()))
    old_status = entry.status
    entry.status = AgentStatus.busy
    coord._stop.set()
    try:
        t0 = __import__("time").time()
        coord._wait_for_ready(block=True)   # must return promptly
        assert __import__("time").time() - t0 < coord.time_out_non_responders
        assert entry.status is AgentStatus.busy  # untouched: just abandoned
    finally:
        coord._stop.clear()
        entry.status = old_status


def test_realtime_coordinator_terminate_joins_worker():
    """Realtime coordinator thread lifecycle without any backend: start
    the wall-clock driver, then terminate() must join the worker."""
    import time as _t

    from agentlib_mpc_tpu.runtime.agent import Agent
    from agentlib_mpc_tpu.runtime.environment import Environment

    env = Environment({"rt": True, "factor": 1.0})
    agent = Agent(env=env, config={"id": "Coord", "modules": []})
    from agentlib_mpc_tpu.modules.coordinator import ADMMCoordinator

    coord = ADMMCoordinator(
        {"module_id": "coordinator", "type": "admm_coordinator",
         "time_step": 5.0, "prediction_horizon": 4}, agent)
    gen = coord._realtime_process()
    next(gen)                                   # starts the worker thread
    worker = coord._thread
    assert worker is not None and worker.is_alive()
    coord.terminate()
    deadline = _t.time() + 5.0
    while _t.time() < deadline and worker.is_alive():
        _t.sleep(0.05)
    assert not worker.is_alive()
    coord.terminate()                           # idempotent


def test_deregistration_telemetry_and_readmission(caplog):
    """ISSUE 2 satellite: every de-registration counts into
    ``coordinator_deregistrations_total{agent=...}`` with ONE rate-limited
    warning per agent, and a de-registered participant is re-admitted at
    the next round's start-iteration sync instead of staying dropped."""
    import logging

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.modules.coordinator import (
        ADMMCoordinator,
        AgentEntry,
        CoordinatorStatus,
    )
    from agentlib_mpc_tpu.runtime.agent import Agent
    from agentlib_mpc_tpu.runtime.environment import Environment
    from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source

    telemetry.configure(enabled=True)
    env = Environment({"rt": False})
    agent = Agent(env=env, config={"id": "Coord", "modules": []})
    coord = ADMMCoordinator(
        {"module_id": "coordinator", "type": "admm_coordinator",
         "time_step": 5.0, "prediction_horizon": 4}, agent)
    src = Source(agent_id="SlowRoom", module_id="admm")
    coord.agent_dict[src] = AgentEntry(source=src,
                                       status=AgentStatus.busy)
    before = telemetry.metrics().get(
        "coordinator_deregistrations_total", agent="SlowRoom") or 0.0

    with caplog.at_level(logging.DEBUG):
        coord._deregister_slow()                    # round 1: slow
        coord.agent_dict[src].status = AgentStatus.busy
        coord._deregister_slow()                    # round 2: slow again
    assert telemetry.metrics().get(
        "coordinator_deregistrations_total", agent="SlowRoom") == before + 2
    assert coord.agent_dict[src].missed_rounds == 2
    warnings = [r for r in caplog.records
                if r.levelno == logging.WARNING
                and "de-registered slow agent" in r.message]
    assert len(warnings) == 1, "warning must be rate-limited to one/agent"

    # re-admission: standby → ready on the next round's sync reply
    assert coord.agent_dict[src].status is AgentStatus.standby
    coord.status = CoordinatorStatus.init_iterations
    coord.init_iteration_callback(AgentVariable(
        name="startIteration_agent_to_coordinator",
        alias="startIteration_agent_to_coordinator",
        value=True, source=src))
    assert coord.agent_dict[src].status is AgentStatus.ready
