"""Declarative dynamic models as pure JAX functions.

Re-design of the reference's ``CasadiModel``
(``agentlib_mpc/models/casadi_model.py:277-584``): there, a user subclasses
the model, declares typed variables in a pydantic config, and assembles
symbolic CasADi equations once in ``setup_system``. Here the same declarative
surface exists — variable lists as class attributes, a ``setup`` method that
writes ODEs / output equations / constraints / objective — but ``setup`` is a
*pure function re-executed inside every JAX trace* with the current stage
values bound to an attribute namespace. No symbolic graph is stored; XLA sees
ordinary jnp arithmetic, which it can fuse, differentiate and vmap.

Semantics preserved from the reference:
- states with no ODE assigned are stage-wise free variables (slacks /
  algebraics) in the OCP (``casadi_model.py:469-500``)
- outputs carry explicit algebraic equations (``CasadiOutput.alg``,
  ``casadi_model.py:242-274``)
- constraints are (lb, expr, ub) triples whose bounds may be expressions
  (``casadi_model.py:458-467``)
- the objective may be a composable `Objective` or a bare scalar
  (legacy wrap: ``casadi_model.py:332-344``)
- name shadowing between variable groups is rejected
  (``casadi_model.py:353-372,574-583``)
- ``simulate_step`` sub-steps dt like ``CasadiModel.do_step``
  (``casadi_model.py:383-400``), with an RK4 scan replacing CVODES.
"""

from __future__ import annotations

import math
from typing import Callable, Iterable, Sequence

import jax
import jax.numpy as jnp

from agentlib_mpc_tpu.models.objective import Objective, _as_objective
from agentlib_mpc_tpu.models.variables import Var


class ModelEquations:
    """Container the user's ``setup`` fills in.

    ``odes``: state name → dx/dt expression
    ``outputs``: output name → algebraic expression
    ``constraints``: list of (lb, expr, ub); bounds may be traced values
    ``objective``: `Objective` | scalar | None (stage cost integrand)
    """

    def __init__(self):
        self.odes: dict[str, jnp.ndarray] = {}
        self.outputs: dict[str, jnp.ndarray] = {}
        self.constraints: list[tuple] = []
        self.objective = None

    def ode(self, name: str, expr) -> None:
        self.odes[name] = expr

    def alg(self, name: str, expr) -> None:
        self.outputs[name] = expr

    def constraint(self, lb, expr, ub) -> None:
        self.constraints.append((lb, expr, ub))


class VarNS:
    """Attribute namespace binding variable names to current (traced) values.

    Plays the role of the reference's operator-overloaded CasadiVariable
    attributes (``casadi_model.py:36-152``): inside ``setup`` the user writes
    ``v.T_in - v.T`` and gets ordinary jnp arithmetic.
    """

    def __init__(self, values: dict[str, jnp.ndarray],
                 du: dict[str, jnp.ndarray] | None = None,
                 t: jnp.ndarray | float = 0.0):
        object.__setattr__(self, "_values", values)
        object.__setattr__(self, "_du", du or {})
        object.__setattr__(self, "t", t)

    def __getattr__(self, name: str):
        try:
            return object.__getattribute__(self, "_values")[name]
        except KeyError:
            raise AttributeError(
                f"model has no variable {name!r}; declared: "
                f"{sorted(object.__getattribute__(self, '_values'))}"
            ) from None

    def __setattr__(self, name, value):
        raise AttributeError("VarNS is read-only; write equations via ModelEquations")

    def __getitem__(self, name: str):
        return self._values[name]

    def du(self, name: str):
        """Control move u_k − u_{k−1} for change penalties (zero outside the
        optimizer — e.g. during plant simulation)."""
        return self._du.get(name, jnp.asarray(0.0))


def _names(vars_: Iterable[Var]) -> list[str]:
    return [v.name for v in vars_]


class Model:
    """Base class for declarative models.

    Subclass and set the class attributes ``inputs``, ``states``,
    ``parameters``, ``outputs`` (lists of `Var`), then implement
    ``setup(self, v) -> ModelEquations``.
    """

    inputs: Sequence[Var] = ()
    states: Sequence[Var] = ()
    parameters: Sequence[Var] = ()
    outputs: Sequence[Var] = ()
    dt: float = 1.0  # native sampling time (ML models override; sim substep)

    def __init__(self, overrides: dict[str, float] | None = None, dt: float | None = None):
        # instantiate per-object copies so overrides don't leak across instances
        self.inputs = [Var.from_dict(v.as_dict()) if isinstance(v, Var) else Var.from_dict(v, "input")
                       for v in type(self).inputs]
        self.states = [Var.from_dict(v.as_dict()) if isinstance(v, Var) else Var.from_dict(v, "state")
                       for v in type(self).states]
        self.parameters = [Var.from_dict(v.as_dict()) if isinstance(v, Var) else Var.from_dict(v, "parameter")
                           for v in type(self).parameters]
        self.outputs = [Var.from_dict(v.as_dict()) if isinstance(v, Var) else Var.from_dict(v, "output")
                        for v in type(self).outputs]
        if dt is not None:
            self.dt = dt
        if overrides:
            self._apply_overrides(overrides)
        self._check_shadowing()
        self.input_names = _names(self.inputs)
        self.state_names = _names(self.states)
        self.parameter_names = _names(self.parameters)
        self.output_names = _names(self.outputs)
        self._probe()

    # -- declaration handling -------------------------------------------------

    def _apply_overrides(self, overrides: dict[str, float]) -> None:
        groups = (self.inputs, self.states, self.parameters, self.outputs)
        byname = {v.name: (g, i) for g in groups for i, v in enumerate(g)}
        for name, val in overrides.items():
            if name not in byname:
                raise KeyError(f"override for unknown variable {name!r}")
            g, i = byname[name]
            if isinstance(val, dict):
                g[i] = Var.from_dict({**g[i].as_dict(), **val}, g[i].role)
            else:
                g[i] = g[i].replace(value=float(val))

    def _check_shadowing(self) -> None:
        seen: set[str] = set()
        for v in (*self.inputs, *self.states, *self.parameters, *self.outputs):
            if v.name in seen:
                raise ValueError(f"duplicate variable name {v.name!r} across groups")
            seen.add(v.name)

    def _probe(self) -> None:
        """Run setup once on defaults to learn the equation structure:
        which states are differential vs. free, constraint count, term names."""
        ns = self._make_ns(
            {v.name: jnp.asarray(float(v.value)) for v in
             (*self.inputs, *self.states, *self.parameters, *self.outputs)})
        eq = self.setup(ns)
        unknown = set(eq.odes) - set(self.state_names)
        if unknown:
            raise ValueError(f"ODE assigned to undeclared states: {sorted(unknown)}")
        unknown = set(eq.outputs) - set(self.output_names)
        if unknown:
            raise ValueError(f"alg equation for undeclared outputs: {sorted(unknown)}")
        self.diff_state_names = [n for n in self.state_names if n in eq.odes]
        self.free_state_names = [n for n in self.state_names if n not in eq.odes]
        self.n_diff = len(self.diff_state_names)
        self.n_free = len(self.free_state_names)
        self.n_constraints = len(eq.constraints)
        obj = eq.objective
        self.objective_term_names = (
            list(_as_objective(obj).term_values().keys()) if obj is not None else [])

    def setup(self, v: VarNS) -> ModelEquations:  # pragma: no cover - abstract
        raise NotImplementedError

    # -- traced evaluation ----------------------------------------------------

    def _make_ns(self, values, du=None, t=0.0) -> VarNS:
        return VarNS(values, du=du, t=t)

    def _bind(self, x_diff, z_free, u, p, t, du=None) -> tuple[ModelEquations, VarNS]:
        values: dict[str, jnp.ndarray] = {}
        for i, n in enumerate(self.diff_state_names):
            values[n] = x_diff[i]
        for i, n in enumerate(self.free_state_names):
            values[n] = z_free[i]
        for i, n in enumerate(self.input_names):
            values[n] = u[i]
        for i, n in enumerate(self.parameter_names):
            values[n] = p[i]
        # outputs start at placeholder defaults; a second setup pass rebinds
        # them to their computed algebraic expressions so constraints and
        # objectives may reference outputs by name (the reference gets this
        # for free from the shared symbolic graph, casadi_model.py:242-274)
        for v in self.outputs:
            values[v.name] = jnp.asarray(float(v.value))
        du_map = None
        if du is not None:
            du_map = {n: du[i] for i, n in enumerate(self.input_names)}
        ns = self._make_ns(values, du=du_map, t=t)
        eq = self.setup(ns)
        # one extra pass per declared output resolves chains of
        # output-to-output references (A=f(x), B=g(A), C=h(B), ...); XLA
        # dedupes the repeated tracing
        for _ in range(len(self.outputs)):
            if not eq.outputs:
                break
            values = dict(values)
            for name, expr in eq.outputs.items():
                values[name] = jnp.asarray(expr)
            ns = self._make_ns(values, du=du_map, t=t)
            eq = self.setup(ns)
        return eq, ns

    def ode(self, x_diff, z_free, u, p, t=0.0):
        """dx/dt of the differential states. Shapes: (n_diff,), (n_free,),
        (n_inputs,), (n_params,) → (n_diff,)."""
        eq, _ = self._bind(x_diff, z_free, u, p, t)
        if not self.diff_state_names:
            return jnp.zeros((0,))
        return jnp.stack([jnp.asarray(eq.odes[n]) for n in self.diff_state_names])

    def output(self, x_diff, z_free, u, p, t=0.0):
        """(n_outputs,) algebraic outputs."""
        eq, _ = self._bind(x_diff, z_free, u, p, t)
        outs = []
        for v in self.outputs:
            if v.name in eq.outputs:
                outs.append(jnp.asarray(eq.outputs[v.name]))
            else:
                outs.append(jnp.asarray(float(v.value)))
        return jnp.stack(outs) if outs else jnp.zeros((0,))

    def constraint_residuals(self, x_diff, z_free, u, p, t=0.0):
        """All model constraints as one-sided residuals h ≥ 0.

        Each (lb, expr, ub) triple contributes ``expr − lb`` and/or
        ``ub − expr``; statically infinite bounds are dropped. Bounds that are
        traced expressions (e.g. a comfort band that is itself a model input,
        as in the reference one-room example) are kept as nonlinear residuals.
        """
        eq, _ = self._bind(x_diff, z_free, u, p, t)
        res = []
        for lb, expr, ub in eq.constraints:
            expr = jnp.asarray(expr)
            if not (isinstance(lb, (int, float)) and math.isinf(lb)):
                res.append(expr - lb)
            if not (isinstance(ub, (int, float)) and math.isinf(ub)):
                res.append(ub - expr)
        return jnp.stack(res) if res else jnp.zeros((0,))

    def stage_cost(self, x_diff, z_free, u, p, t=0.0, du=None):
        """Objective integrand at one stage → scalar."""
        if du is None:
            du = jnp.zeros((len(self.input_names),))
        eq, _ = self._bind(x_diff, z_free, u, p, t, du=du)
        if eq.objective is None:
            return jnp.asarray(0.0)
        return jnp.asarray(_as_objective(eq.objective).value())

    def stage_cost_terms(self, x_diff, z_free, u, p, t=0.0, du=None):
        """name → weighted per-term stage cost (for stats, reference
        ``casadi_backend.py:309-323``)."""
        if du is None:
            du = jnp.zeros((len(self.input_names),))
        eq, _ = self._bind(x_diff, z_free, u, p, t, du=du)
        if eq.objective is None:
            return {}
        return {k: jnp.asarray(v) for k, v in
                _as_objective(eq.objective).term_values().items()}

    # -- simulation (plant stand-in; replaces CVODES do_step) -----------------

    def simulate_step(self, x_diff, u, p, dt: float, substeps: int = 10,
                      method: str = "rk4"):
        """Integrate the ODE over one sample with fixed sub-steps
        (reference ``CasadiModel.do_step``, ``casadi_model.py:383-400``).

        `method` selects the stepper from ops.integrators ("euler", "rk4",
        "implicit_midpoint" for stiff plants — the CVODES stand-ins). Free
        (slack) states are held at zero during simulation. Returns
        (x_next, outputs).
        """
        from agentlib_mpc_tpu.ops.integrators import integrate

        z = jnp.zeros((self.n_free,))

        def f(x, t):
            return self.ode(x, z, u, p, t)

        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        x_next = integrate(f, jnp.asarray(x_diff, dtype=dtype), 0.0, dt,
                           substeps=substeps, method=method)
        y = self.output(x_next, z, u, p, dt)
        return x_next, y

    # -- convenience ----------------------------------------------------------

    def default_vector(self, group: str) -> jnp.ndarray:
        vars_ = {"inputs": self.inputs, "parameters": self.parameters,
                 "outputs": self.outputs}.get(group)
        if group == "diff_states":
            byname = {v.name: v for v in self.states}
            vars_ = [byname[n] for n in self.diff_state_names]
        elif group == "free_states":
            byname = {v.name: v for v in self.states}
            vars_ = [byname[n] for n in self.free_state_names]
        if vars_ is None:
            raise KeyError(group)
        return jnp.array([float(v.value) for v in vars_])

    def get_var(self, name: str) -> Var:
        for v in (*self.inputs, *self.states, *self.parameters, *self.outputs):
            if v.name == name:
                return v
        raise KeyError(name)

    def input_index(self, name: str) -> int:
        return self.input_names.index(name)
