"""End-to-end containerized-fleet run, degraded to process boundaries.

VERDICT r4 #4: prove the deploy/ fleet recipe — coordinator + two agents
completing full coordinated-ADMM rounds over MQTT across
container/process boundaries, with recorded results CSVs. Docker is not
available in this image, so this is the CI-runnable variant the compose
file documents: the SAME entry points (``runtime/container.py`` mains,
``runtime/mqtt_native`` broker), the SAME JSON configs
(``deploy/fleet/*.json``), real MQTT frames over real TCP — only the
container boundary is a process boundary.
"""

import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]


def _spawn_agent(config: Path, port: int, results_dir: Path, until: float):
    from agentlib_mpc_tpu.utils.jax_setup import cpu_subprocess_env

    env = cpu_subprocess_env()
    env.update({
        "PYTHONPATH": str(REPO),
        "AGENT_CONFIG": str(config),
        "MQTT_HOST": "127.0.0.1",
        "MQTT_PORT": str(port),
        "REALTIME": "1",
        "RUN_UNTIL": str(until),
        "RESULTS_DIR": str(results_dir),
        "LOG_LEVEL": "INFO",
    })
    return subprocess.Popen(
        [sys.executable, "-m", "agentlib_mpc_tpu.runtime.container"],
        env=env, cwd=str(REPO), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


@pytest.mark.slow
def test_coordinated_admm_fleet_across_process_boundaries(tmp_path):
    import pandas as pd

    from agentlib_mpc_tpu.runtime.mqtt_native import MiniBroker

    broker = MiniBroker()
    results = tmp_path / "results"
    procs = {}
    try:
        # the coordinator gets a longer horizon: the agent processes
        # spend their first wall-seconds compiling their backends
        # (precompile: true) on this 1-core VM before they register
        procs["coordinator"] = _spawn_agent(
            REPO / "deploy/fleet/coordinator.json", broker.port, results,
            until=150.0)
        procs["room"] = _spawn_agent(
            REPO / "deploy/fleet/room.json", broker.port, results,
            until=45.0)
        procs["cooler"] = _spawn_agent(
            REPO / "deploy/fleet/cooler.json", broker.port, results,
            until=45.0)

        # room + cooler exit after their RUN_UNTIL; the coordinator may
        # still be mid-horizon — once both agents are down it has nothing
        # to coordinate, so terminate it gracefully (SIGTERM is the
        # docker-stop path the entry point handles)
        for name in ("room", "cooler"):
            out, _ = procs[name].communicate(timeout=600)
            assert procs[name].returncode == 0, f"{name} failed:\n{out}"
        procs["coordinator"].terminate()
        out_c, _ = procs["coordinator"].communicate(timeout=60)
        assert procs["coordinator"].returncode == 0, \
            f"coordinator failed:\n{out_c}"

        assert broker.messages_routed > 0, "no MQTT traffic crossed TCP"

        # recorded results CSVs (the reference's results artifacts)
        coord_csv = results / "Coordinator__coordinator.csv"
        assert coord_csv.exists(), \
            f"coordinator wrote no stats CSV; its log:\n{out_c[-3000:]}"
        stats = pd.read_csv(coord_csv)
        assert {"primal_residual", "dual_residual",
                "penalty_parameter"} <= set(stats.columns)
        assert len(stats) >= 1, "no completed ADMM iteration was recorded"
        assert "registered agent" in out_c, out_c[-3000:]
        for agent in ("CooledRoom", "Cooler"):
            assert f"Source(agent_id='{agent}'" in out_c or \
                agent in out_c, f"{agent} never registered:\n{out_c[-3000:]}"
        room_csv = results / "CooledRoom__admm.csv"
        if room_csv.exists():      # written when ≥1 local solve recorded
            assert room_csv.stat().st_size > 0
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
        broker.stop()
