"""Sharded fused-ADMM fleet (ISSUE 9): ``shard_map`` agent axis + psum
consensus.

Pins the mesh execution path of :class:`FusedADMM` on the 8-virtual-
device CPU mesh the conftest provisions: sharded-vs-unsharded identity
(tracker fleet in tier-1; the example-OCP menu entries — QP fast path
AND interior-point — in the slow tier, where their engine compiles
belong), a multi-group fleet with both coupling kinds, the
non-divisible padding fix in ``shard_args`` (pad + warn, never silently
replicate), quarantine attribution across shards, the mesh-aware
serving slot multiple, a mesh-backed ``ServingPlane`` churning at zero
retraces, and the ``[mesh]`` retrace-budget gate (slow here; the CI
lint job runs it on every PR).

Multi-group fleets use a 4-device mesh: cross-group concatenation into
the consensus collective needs every device thread at one rendezvous,
and on this box an 8-way rendezvous under load intermittently starves
(the documented ``test_padded_unequal_groups_shard_on_mesh`` flake);
4 devices exercise identical sharding semantics.

Engine builds dominate this file's cost (Python tracing of the IPM is
not covered by the persistent XLA cache), so the tracker fleet pair is
a module fixture shared by the identity / quarantine / telemetry tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.ops.transcription import transcribe
from agentlib_mpc_tpu.parallel import (
    fleet_mesh,
    serving_slot_multiple,
    shard_multiple,
)
from agentlib_mpc_tpu.parallel.fused_admm import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
    pad_group_to_devices,
    stack_params,
)

from conftest import make_tracker_model  # noqa: E402

SOLVER = SolverOptions(tol=1e-8, max_iter=30)
OPTS = FusedADMMOptions(max_iterations=20, rho=2.0, abs_tol=1e-6,
                        rel_tol=1e-5)

Tracker = make_tracker_model(lb=-10.0, ub=10.0)


@pytest.fixture(scope="module")
def tracker_ocp():
    return transcribe(Tracker(), ["u"], N=4, dt=300.0,
                      method="multiple_shooting")


def tracker_thetas(ocp, targets):
    return stack_params([
        ocp.default_params(p=jnp.array([float(t)])) for t in targets])


@pytest.fixture(scope="module")
def tracker_pair(tracker_ocp, eight_devices):
    """(plain engine, mesh engine, thetas) for the 8-tracker consensus
    fleet — built ONCE; the identity, quarantine and telemetry tests
    share the warm executables."""
    group = AgentGroup(name="trackers", ocp=tracker_ocp, n_agents=8,
                       couplings={"c": "u"}, solver_options=SOLVER)
    thetas = tracker_thetas(tracker_ocp, range(8))
    plain = FusedADMM([group], OPTS)
    meshed = FusedADMM([group], OPTS, mesh=fleet_mesh())
    return plain, meshed, thetas


class TestShardedIdentity:
    def test_tracker_mesh_matches_single_device(self, tracker_pair):
        plain, meshed, thetas = tracker_pair
        rs, rt, rstat = plain.step(plain.init_state([thetas]), [thetas])
        ms, mt, mstat = meshed.step(meshed.init_state([thetas]), [thetas])
        assert bool(mstat.converged) == bool(rstat.converged)
        assert int(mstat.iterations) == int(rstat.iterations)
        np.testing.assert_allclose(np.asarray(ms.zbar["c"]),
                                   np.asarray(rs.zbar["c"]), atol=1e-8)
        np.testing.assert_allclose(np.asarray(mt[0]["u"]),
                                   np.asarray(rt[0]["u"]), atol=1e-6)
        # the analytic consensus fixed point survives the mesh
        np.testing.assert_allclose(np.asarray(ms.zbar["c"]), 3.5,
                                   atol=1e-3)

    @pytest.mark.slow
    @pytest.mark.parametrize("name,control", [
        ("LinearRCZone/colloc-d1", "Q"),       # LQ: the QP fast path
        ("OneRoom/shooting", "mDot"),          # bilinear: interior point
    ])
    def test_menu_entry_mesh_matches_single_device(self, eight_devices,
                                                   name, control):
        """Example-menu identity: the sharded engine must reproduce the
        single-device fleet on both solver routings (the jaxpr-certified
        QP fast path and the IPM path)."""
        from agentlib_mpc_tpu.lint.jaxpr.examples import build_example

        ocp = build_example(name)
        group = AgentGroup(name=name, ocp=ocp, n_agents=8,
                           couplings={"shared": control},
                           solver_options=SolverOptions(max_iter=25))
        theta0 = ocp.default_params()
        thetas = stack_params([
            ocp.default_params(x0=theta0.x0 * (1.0 + 0.002 * i))
            for i in range(8)])
        opts = FusedADMMOptions(max_iterations=4, rho=1e-2)
        ref = FusedADMM([group], opts)
        rs, rt, rstat = ref.step(ref.init_state([thetas]), [thetas])

        eng = FusedADMM([group], opts, mesh=fleet_mesh())
        ms, mt, mstat = eng.step(eng.init_state([thetas]), [thetas])
        assert int(mstat.iterations) == int(rstat.iterations)
        np.testing.assert_allclose(
            np.asarray(ms.zbar["shared"]), np.asarray(rs.zbar["shared"]),
            rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(
            np.asarray(mt[0]["u"]), np.asarray(rt[0]["u"]),
            rtol=1e-5, atol=1e-7)

    def test_multi_group_exchange_mesh_matches(self, eight_devices,
                                               tracker_ocp):
        """Two structure groups (consensus) + an exchange coupling on a
        4-device mesh: both collective kinds (psum'ed masked means AND
        the shared exchange multiplier) reproduce the unsharded fleet."""
        ga = AgentGroup(name="a", ocp=tracker_ocp, n_agents=4,
                        couplings={"c": "u"}, solver_options=SOLVER)
        gb = AgentGroup(name="b", ocp=tracker_ocp, n_agents=4,
                        exchanges={"bal": "u"}, solver_options=SOLVER)
        ta = tracker_thetas(tracker_ocp, (0.0, 1.0, 2.0, 3.0))
        tb = tracker_thetas(tracker_ocp, (4.0, 5.0, 6.0, 7.0))
        ref = FusedADMM([ga, gb], OPTS)
        rs, _rt, rstat = ref.step(ref.init_state([ta, tb]), [ta, tb])

        mesh = Mesh(np.array(eight_devices[:4]), ("agents",))
        eng = FusedADMM([ga, gb], OPTS, mesh=mesh)
        ms, _mt, mstat = eng.step(eng.init_state([ta, tb]), [ta, tb])
        assert int(mstat.iterations) == int(rstat.iterations)
        np.testing.assert_allclose(np.asarray(ms.zbar["c"]),
                                   np.asarray(rs.zbar["c"]), atol=1e-8)
        np.testing.assert_allclose(np.asarray(ms.ex_mean["bal"]),
                                   np.asarray(rs.ex_mean["bal"]),
                                   atol=1e-8)
        np.testing.assert_allclose(np.asarray(ms.ex_lam["bal"]),
                                   np.asarray(rs.ex_lam["bal"]),
                                   atol=1e-8)

    def test_quarantine_attribution_across_shards(self, tracker_pair):
        """A NaN-poisoned lane on a NON-zero shard is quarantined, its
        lane attribution lands at the right global row, and the fleet's
        carried state stays finite — the psum'ed health counters and the
        sharded ``lane_quarantined`` out-spec both proven. (Poisons the
        warm start like test_chaos.py's quarantine pins — a NaN iterate
        deterministically yields a NaN local solution.)"""
        _plain, eng, thetas = tracker_pair
        state = eng.init_state([thetas])
        state, _t, _s = eng.step(state, [thetas])
        victim = 6                     # lives on device 6, not device 0
        state = state._replace(
            w=(state.w[0].at[victim].set(jnp.nan),))
        state, trajs, stats = eng.step(state, [thetas])
        lane_q = np.asarray(stats.lane_quarantined[0])
        assert lane_q.shape == (8,)
        assert lane_q[victim] > 0
        assert (lane_q[[i for i in range(8) if i != victim]] == 0).all()
        assert int(np.asarray(stats.quarantined).sum()) > 0
        assert all(bool(jnp.all(jnp.isfinite(leaf)))
                   for leaf in jax.tree.leaves(state))
        healthy = np.asarray(trajs[0]["u"])[
            [i for i in range(8) if i != victim]]
        assert np.isfinite(healthy).all()


class TestShardArgsPadding:
    def test_non_divisible_group_is_padded_not_replicated(
            self, eight_devices, tracker_ocp, caplog):
        """Satellite 1: shard_args on a 6-agent group over the 8-device
        mesh pads 2 masked lanes (one warning stating the overhead) and
        actually shards the agent axis; results match the unpadded
        single-device fleet."""
        import logging

        targets = range(6)
        group = AgentGroup(name="six", ocp=tracker_ocp, n_agents=6,
                           couplings={"c": "u"}, solver_options=SOLVER)
        thetas = tracker_thetas(tracker_ocp, targets)
        ref = FusedADMM([group], OPTS)
        rs, rt, _ = ref.step(ref.init_state([thetas]), [thetas])

        eng = FusedADMM([group], OPTS)
        with caplog.at_level(logging.WARNING,
                             logger="agentlib_mpc_tpu.parallel.fused_admm"):
            st, th = eng.shard_args(fleet_mesh(), eng.init_state([thetas]),
                                    [thetas])
        warnings = [r for r in caplog.records if "padding" in r.message]
        assert len(warnings) == 1
        assert eng.groups[0].n_agents == 8
        assert np.asarray(eng.active[0]).tolist() == [True] * 6 + [False] * 2
        assert not st.w[0].sharding.is_fully_replicated
        ps, pt, pstat = eng.step(st, th)
        assert bool(pstat.converged)
        np.testing.assert_allclose(np.asarray(ps.zbar["c"]),
                                   np.asarray(rs.zbar["c"]), atol=1e-6)
        np.testing.assert_allclose(np.asarray(pt[0]["u"])[:6],
                                   np.asarray(rt[0]["u"]), atol=1e-5)

    def test_mesh_engine_rejects_non_divisible_group(self, eight_devices,
                                                     tracker_ocp):
        group = AgentGroup(name="six", ocp=tracker_ocp, n_agents=6,
                           couplings={"c": "u"}, solver_options=SOLVER)
        with pytest.raises(ValueError, match="pad_group_to_devices"):
            FusedADMM([group], OPTS, mesh=fleet_mesh())

    def test_mesh_engine_rejects_record_locals(self, eight_devices,
                                               tracker_ocp):
        group = AgentGroup(name="t", ocp=tracker_ocp, n_agents=8,
                           couplings={"c": "u"}, solver_options=SOLVER)
        with pytest.raises(ValueError, match="record_locals"):
            FusedADMM([group], OPTS, mesh=fleet_mesh(),
                      record_locals=True)

    @pytest.mark.slow
    def test_padded_group_on_mesh_engine(self, eight_devices,
                                         tracker_ocp):
        """The pad_group_to_devices -> mesh-engine recipe (the module
        docstring launch sequence): a 6-agent fleet padded to 8 runs the
        shard_map path and matches the unpadded single-device result.
        Built with ``quarantine=False`` to ALSO pin that a mesh engine
        without the quarantine stats (``lane_quarantined=None``) still
        compiles and steps — the out-specs must match the None subtree."""
        no_q = OPTS._replace(quarantine=False)
        group = AgentGroup(name="six", ocp=tracker_ocp, n_agents=6,
                           couplings={"c": "u"}, solver_options=SOLVER)
        thetas = tracker_thetas(tracker_ocp, range(6))
        ref = FusedADMM([group], no_q)
        rs, _rt, _ = ref.step(ref.init_state([thetas]), [thetas])

        padded, thetas_p, mask = pad_group_to_devices(group, thetas, 8)
        eng = FusedADMM([padded], no_q, active=[mask], mesh=fleet_mesh())
        ms, _mt, mstat = eng.step(eng.init_state([thetas_p]), [thetas_p])
        assert bool(mstat.converged)
        assert mstat.lane_quarantined is None
        np.testing.assert_allclose(np.asarray(ms.zbar["c"]),
                                   np.asarray(rs.zbar["c"]), atol=1e-6)


class TestPadPathUnderChurn:
    def test_padded_group_survives_shard_loss_and_repads(
            self, eight_devices, tracker_ocp, compile_profiler):
        """ISSUE 10 satellite: the shard_args pad path under churn — a
        NON-divisible 6-agent group (padded 6->8 on the full mesh)
        loses a shard, re-pads onto the 7-survivor mesh (6->7 rows),
        and re-admits. Masked-lane invariance: every cycle's results
        match the unpadded single-device fleet; and the SECOND
        degrade -> serve -> re-admit -> serve cycle runs at zero
        retraces (layouts cached per surviving-device set)."""
        from agentlib_mpc_tpu.lint.retrace_budget import _compile_snapshot
        from agentlib_mpc_tpu.parallel.survival import FleetSupervisor

        group = AgentGroup(name="six", ocp=tracker_ocp, n_agents=6,
                           couplings={"c": "u"}, solver_options=SOLVER)
        thetas = [tracker_thetas(tracker_ocp, range(6))]
        ref = FusedADMM([group], OPTS)

        sup = FleetSupervisor([group], OPTS, mesh=fleet_mesh(),
                              watchdog_timeout_s=60.0, readmit_after=1,
                              probation_rounds=1)
        # full layout pads 6 -> 8 (1 agent/device): device 3 hosts
        # agent 3, which the degrade masks out
        dead = sup.full_mesh.devices.flat[3].id
        all_on = jnp.ones((6,), bool)
        survivors = all_on.at[3].set(False)

        def one_round(state, mask, transition=False):
            """The supervisor's round vs the unpadded single-device
            fleet stepping the SAME state with the SAME mask —
            masked-lane invariance: neither the full-mesh 6->8 pad nor
            the degraded 6->7 re-pad may leak into the result.
            ``transition``: the supervisor re-centers the consensus
            multipliers when the active set changes (the conserved-sum
            invariant); the reference must start from the same
            re-centered state to compare like with like."""
            s2, trajs, stats = sup.step(state, thetas)
            ref_in = sup._recenter_consensus_multipliers(
                state, [mask]) if transition else state
            r2, rtraj, _ = ref.step(ref_in, thetas, active=[mask])
            assert bool(stats.converged)
            np.testing.assert_allclose(np.asarray(s2.zbar["c"]),
                                       np.asarray(r2.zbar["c"]),
                                       atol=1e-8)
            act = np.asarray(mask)
            np.testing.assert_allclose(
                np.asarray(trajs[0]["u"])[act],
                np.asarray(rtraj[0]["u"])[act], atol=1e-6)
            return s2

        # warmup cycle: full layout, degraded layout (the one
        # legitimate rebuild), re-admission
        state = one_round(sup.init_state(thetas), all_on)
        sup.force_degrade([dead])
        assert sup.engine.groups[0].n_agents == 7   # re-pad onto 7 devs
        state = one_round(state, survivors, transition=True)
        sup.force_readmit()
        # the post-readmit rounds reset the lost lane's warm start and
        # re-balance the multipliers from the 5-agent equilibrium back
        # to the 6-agent one; assert recovery against the analytic
        # consensus fixed point (mean of the 6 targets)
        for _ in range(3):
            state, _trajs, stats = sup.step(state, thetas)
            assert bool(stats.converged)
        np.testing.assert_allclose(np.asarray(state.zbar["c"]), 2.5,
                                   atol=2e-2)

        before = _compile_snapshot(compile_profiler)
        sup.force_degrade([dead])
        state = one_round(state, survivors, transition=True)
        sup.force_readmit()
        state, _trajs, stats = sup.step(state, thetas)
        assert bool(stats.converged)
        after = _compile_snapshot(compile_profiler)
        deltas = {k: after.get(k, 0) - before.get(k, 0)
                  for k in set(before) | set(after)}
        assert all(v == 0 for v in deltas.values()), deltas
        assert sup.stats()["layouts_built"] == 2


class TestMeshServing:
    def test_serving_slot_multiple_is_mesh_aware(self, eight_devices):
        n_dev = len(jax.devices())
        assert serving_slot_multiple() == n_dev
        mesh4 = Mesh(np.array(eight_devices[:4]), ("agents",))
        assert shard_multiple(mesh4) == 4
        # lcm(device count, mesh size): capacities built at this
        # granularity divide BOTH the mesh and the full device set
        assert serving_slot_multiple(mesh4) == np.lcm(n_dev, 4)
        assert serving_slot_multiple(fleet_mesh()) == np.lcm(n_dev, n_dev)

    def test_serving_plane_on_mesh_churn_zero_retraces(
            self, eight_devices, compile_profiler):
        """Satellite 2 acceptance: join/serve/leave tenants on a
        forced-8-device mesh at zero retraces — membership on a SHARDED
        bucket engine is still data, never structure."""
        from agentlib_mpc_tpu.lint.retrace_budget import (
            _compile_snapshot,
            serve_tenants,
            tracker_ocp,
            tracker_tenant_spec,
        )
        from agentlib_mpc_tpu.serving import ServingPlane

        ocp = tracker_ocp()
        plane = ServingPlane(FusedADMMOptions(max_iterations=6, rho=2.0),
                             mesh=fleet_mesh(), pipelined=False,
                             donate=False)

        def spec(tid, a):
            return tracker_tenant_spec(ocp, tid, a)

        def serve(*tenants):
            return serve_tenants(plane, *tenants)

        # bucket capacity honors the mesh multiple
        rec = plane.join(spec("w0", 1.0))
        assert rec.capacity % len(jax.devices()) == 0
        serve("w0")
        serve("w0")                    # second round: steady state
        before = _compile_snapshot(compile_profiler)
        plane.join(spec("t1", 3.0))
        res = serve("w0", "t1")
        assert res["w0"].action == "actuate"
        assert res["t1"].action == "actuate"
        # consensus pulls both tenants toward the shared mean
        assert abs(res["w0"].controls["u"] - res["t1"].controls["u"]) < 0.5
        plane.leave("t1")
        res = serve("w0")
        assert res["w0"].action == "actuate"
        after = _compile_snapshot(compile_profiler)
        deltas = {k: after.get(k, 0) - before.get(k, 0)
                  for k in set(before) | set(after)}
        assert all(v == 0 for v in deltas.values()), deltas

    @pytest.mark.slow
    def test_mesh_gate_passes(self, eight_devices):
        """The ``[mesh]`` budget gate (lint_budgets.toml) holds: zero
        warm retraces of the sharded step and the mesh serving churn —
        the CI lint job runs the real gate on every PR; this pins it in
        the test suite too."""
        from agentlib_mpc_tpu.lint.retrace_budget import run_mesh_gate

        report = run_mesh_gate(budgets={"mesh": {
            "warmup_rounds": 2, "rounds": 2, "n_agents": 8,
            "devices": 8,
            "budgets": {"default": 0, "admm.fused_step": 0},
            "serving": {"budgets": {"default": 0}},
        }}, verbose=False)
        assert report["violations"] == [], report
        assert report["failures"] == [], report
        assert report["mesh_devices"] >= 2


class TestMeshTelemetry:
    def test_collective_probe_and_gauge_recorded(self, compile_profiler,
                                                 tracker_pair):
        """Satellite 3: a mesh engine's round records the
        ``fleet_mesh_devices`` gauge and the ``admm_collective_seconds``
        histogram (the per-round consensus-shaped pmean probe)."""
        from agentlib_mpc_tpu import telemetry

        _plain, eng, thetas = tracker_pair
        eng.step(eng.init_state([thetas]), [thetas])
        reg = telemetry.metrics()
        assert reg.get("fleet_mesh_devices") == float(len(jax.devices()))
        samples = reg.histogram("admm_collective_seconds").samples()
        assert samples and samples[0]["count"] >= 1


@pytest.mark.slow
def test_mesh_ab_smoke(eight_devices):
    """``bench.py --mesh-ab 256`` end to end (the acceptance row's
    machinery): both device counts produce rows, the sharded run agrees
    with the single-device consensus, and keys carry the d<n>
    qualifier."""
    import json
    import os
    import subprocess
    import sys as _sys

    from agentlib_mpc_tpu.utils.jax_setup import cpu_subprocess_env

    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    proc = subprocess.run(
        [_sys.executable, bench, "--worker", "--mesh-ab", "256"],
        capture_output=True, text=True, timeout=3000,
        env=cpu_subprocess_env(), cwd=os.path.dirname(bench))
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(ln) for ln in proc.stdout.splitlines()
            if ln.strip().startswith("{")]
    by_dev = {r["devices"]: r for r in rows}
    assert set(by_dev) == {1, 8}
    assert by_dev[8]["metric"] == "mesh_ab[256,d8]"
    assert by_dev[8]["zbar_max_abs_diff"] < 1e-3
    assert by_dev[8]["identity_ok"] and by_dev[1]["identity_ok"]
    assert by_dev[8]["converged"] and by_dev[1]["converged"]
