"""MINLP/CIA: combinatorial approximation math + mixed-integer MPC loop.

Coverage the reference lacks (its ``tests/test_miqp_backend.py`` is a
commented-out stub, SURVEY.md §4): direct unit tests of the CIA
branch-and-bound (native C++ and Python fallback), sum-up rounding, and a
closed-loop mixed-integer MPC on the switched-cooling zone (reference
example family ``examples/one_room_mpc/mixed_integer``).
"""

import numpy as np
import pytest

from agentlib_mpc_tpu.backends.backend import VariableReference, create_backend
from agentlib_mpc_tpu.models.zoo import SwitchedRoom
from agentlib_mpc_tpu.ops.cia import (
    _solve_python,
    cia_objective,
    solve_cia,
    sum_up_rounding,
)


class TestCIA:
    def test_integral_input_is_fixed_point(self):
        b_rel = np.array([[1.0], [0.0], [1.0], [1.0]])
        B, eta = solve_cia(b_rel, dt=1.0)
        np.testing.assert_allclose(B, b_rel)
        assert eta == pytest.approx(0.0)

    def test_objective_definition(self):
        b_rel = np.array([[0.5], [0.5]])
        B = np.array([[1.0], [0.0]])
        # deviations: -0.5 then 0.0 → max |.| = 0.5
        assert cia_objective(b_rel, B, np.ones(2)) == pytest.approx(0.5)

    def test_halves_schedule(self):
        # 0.5 everywhere → optimal schedule alternates, eta = dt/2
        b_rel = np.full((6, 1), 0.5)
        B, eta = solve_cia(b_rel, dt=2.0)
        assert eta == pytest.approx(1.0)
        assert set(np.unique(B)) <= {0.0, 1.0}

    def test_beats_or_matches_sum_up_rounding(self):
        rng = np.random.default_rng(0)
        for _ in range(5):
            b_rel = rng.uniform(size=(12, 2))
            dt = np.ones(12)
            B, eta = solve_cia(b_rel, dt=1.0)
            sur = sum_up_rounding(b_rel, dt)
            assert eta <= cia_objective(b_rel, sur, dt) + 1e-12

    def test_sos1_one_hot(self):
        rng = np.random.default_rng(1)
        raw = rng.uniform(size=(8, 3))
        b_rel = raw / raw.sum(axis=1, keepdims=True)
        B, eta = solve_cia(b_rel, dt=1.0, sos1=True)
        np.testing.assert_allclose(B.sum(axis=1), 1.0)

    def test_max_switches_respected(self):
        b_rel = np.array([[0.9], [0.1], [0.9], [0.1], [0.9], [0.1]])
        B, _ = solve_cia(b_rel, dt=1.0, max_switches=[2])
        assert int(np.sum(np.abs(np.diff(B[:, 0])))) <= 2

    def test_native_matches_python_fallback(self):
        rng = np.random.default_rng(2)
        b_rel = rng.uniform(size=(10, 2))
        dt = np.ones(10)
        B_n, eta_n = solve_cia(b_rel, dt=1.0)
        B_p, eta_p = _solve_python(b_rel, dt, None, False,
                                   max_nodes=10_000_000)
        # both provably optimal → identical objective
        assert eta_n == pytest.approx(eta_p, abs=1e-12)

    def test_native_library_builds(self):
        from agentlib_mpc_tpu import native

        assert native.load("cia") is not None, \
            "C++ CIA solver failed to build (g++ is in the image)"


class TestSUR:
    def test_tracks_mean(self):
        b_rel = np.full((50, 1), 0.3)
        B = sum_up_rounding(b_rel, np.ones(50))
        assert np.mean(B) == pytest.approx(0.3, abs=0.05)


def _make_bb_backend(horizon: int, bb_options: dict):
    backend = create_backend({
        "type": "jax_minlp_bb",
        "model": {"class": SwitchedRoom},
        "discretization_options": {"method": "multiple_shooting"},
        "solver": {"max_iter": 60},
        "binary_method": "rounding",
        "bb_options": bb_options,
    })
    backend.setup_optimization(
        VariableReference(
            states=["T"], binary_controls=["on"],
            inputs=["load", "T_upper"],
            parameters=["C", "Q_cool", "s_T", "r_on"],
        ),
        time_step=300.0, prediction_horizon=horizon)
    return backend


def _capture_ctx(monkeypatch) -> dict:
    """Spy on BranchAndBoundBackend._schedule to expose the solve's ctx
    (needed to drive the exact evaluator for enumeration proofs)."""
    from agentlib_mpc_tpu.backends.minlp_backend import (
        BranchAndBoundBackend,
    )

    captured = {}
    orig = BranchAndBoundBackend._schedule

    def spy(self, b_rel, ctx):
        captured["ctx"] = ctx
        return orig(self, b_rel, ctx)

    monkeypatch.setattr(BranchAndBoundBackend, "_schedule", spy)
    return captured


@pytest.fixture(scope="module")
def minlp_backend():
    backend = create_backend({
        "type": "jax_cia",
        "model": {"class": SwitchedRoom},
        "discretization_options": {"method": "multiple_shooting"},
        "solver": {"max_iter": 60},
        "cia_options": {"max_switches": 6},
    })
    backend.setup_optimization(
        VariableReference(
            states=["T"],
            controls=[],
            binary_controls=["on"],
            inputs=["load", "T_upper"],
            parameters=["C", "Q_cool", "s_T", "r_on"],
        ),
        time_step=300.0,
        prediction_horizon=8,
    )
    return backend


class TestMINLPBackend:
    def test_solve_returns_binary_schedule(self, minlp_backend):
        result = minlp_backend.solve(0.0, {"T": 296.15})
        B = result["binary_schedule"]
        assert set(np.unique(B)) <= {0.0, 1.0}
        assert result["u0"]["on"] in (0.0, 1.0)
        assert result["stats"]["relaxed_success"]

    def test_hot_room_switches_on(self, minlp_backend):
        # way above the comfort band → chiller must run immediately
        result = minlp_backend.solve(300.0, {"T": 299.15})
        assert result["u0"]["on"] == 1.0

    def test_cold_room_stays_off(self, minlp_backend):
        result = minlp_backend.solve(600.0, {"T": 289.15})
        assert result["u0"]["on"] == 0.0

    def test_closed_loop_respects_comfort(self, minlp_backend):
        model = SwitchedRoom()
        T = 296.65  # slightly hot
        temps = []
        for k in range(12):
            res = minlp_backend.solve(k * 300.0, {"T": T})
            on = res["u0"]["on"]
            x, _ = model.simulate_step(
                np.array([T, 0.0])[:1], np.array([on, 180.0, 295.15]),
                np.array([100000.0, 500.0, 10.0, 0.01]), dt=300.0)
            T = float(x[0])
            temps.append(T)
        # chiller capacity (500 W) beats the load (180 W): the zone must be
        # driven back under the comfort bound and stay in a sane band
        assert temps[-1] < 295.65
        assert all(288.0 < t < 300.0 for t in temps)

    def test_lockout_bound_forces_off(self, minlp_backend):
        # a published ub=0 on the binary (maintenance lock-out) must win
        # even in a hot room
        result = minlp_backend.solve(900.0, {"T": 299.15, "on__ub": 0.0})
        assert result["u0"]["on"] == 0.0
        assert np.all(result["binary_schedule"] == 0.0)

    def test_max_switches_length_mismatch_rejected(self):
        with pytest.raises(ValueError, match="max_switches"):
            solve_cia(np.full((4, 2), 0.5), dt=1.0, max_switches=[2])

    @pytest.mark.slow
    def test_bb_beats_rounding_and_matches_enumeration(self, monkeypatch):
        """The TPU-idiomatic bonmin (reference ``casadi_utils.py:264-280``):
        best-first branch-and-bound over binary fixings, children relaxed
        in one vmapped interior-point call per sweep. Scenario: a
        fractional relaxed duty cycle (~0.36) that plain rounding turns
        into all-off, paying the comfort slack — provably suboptimal by
        exhaustive enumeration of all 2^4 schedules with the same exact
        phase-3 evaluator the search scores incumbents with."""
        import itertools

        backend = _make_bb_backend(
            horizon=4, bb_options={"max_nodes": 64, "batch_pairs": 4})
        captured = _capture_ctx(monkeypatch)
        # room exactly at the comfort bound: holding it needs duty ~0.36
        res = backend.solve(0.0, {"T": 295.15})
        stats = res["stats"]

        # the relaxed duty cycle is fractional; rounding turned the
        # chiller off everywhere and paid the slack — B&B must improve
        b_rel = np.asarray(res["traj_relaxed"]["u"])[:, backend._bin_idx]
        assert 0.05 < float(b_rel.mean()) < 0.95
        assert stats["bb_improved_on_heuristic"]

        # exhaustive optimality proof with the search's own evaluator
        objs = {}
        for bits in itertools.product([0.0, 1.0], repeat=4):
            B = np.array(bits).reshape(4, 1)
            objs[bits] = backend._exact_objective(B, captured["ctx"])
        best = min(objs.values())
        assert stats["bb_incumbent"] == pytest.approx(
            best, rel=1e-3, abs=1e-5)
        assert stats["bb_proven_optimal"]
        # the returned schedule really scores the incumbent objective
        assert backend._exact_objective(
            res["binary_schedule"], captured["ctx"]) == pytest.approx(
            stats["bb_incumbent"], rel=1e-5, abs=1e-7)
        # ... and the heuristic's schedule is strictly worse
        B_round = np.round(np.clip(b_rel, 0.0, 1.0))
        assert objs[tuple(B_round.ravel())] > best + 1e-3

    @pytest.mark.slow
    def test_bb_matches_enumeration_across_scenarios(self, monkeypatch):
        """Property-style hardening of the optimality claim: across
        seeded random initial temperatures and loads, the B&B incumbent
        must match exhaustive enumeration of all 2^3 schedules with its
        own exact evaluator (one compiled backend, scenarios amortize
        the compile)."""
        import itertools

        backend = _make_bb_backend(
            horizon=3, bb_options={"max_nodes": 40, "batch_pairs": 2})
        captured = _capture_ctx(monkeypatch)
        rng = np.random.default_rng(7)
        for k in range(4):
            T0 = float(rng.uniform(294.5, 297.5))
            load = float(rng.uniform(120.0, 400.0))
            res = backend.solve(k * 300.0, {"T": T0, "load": load})
            best = min(
                backend._exact_objective(
                    np.array(bits).reshape(3, 1), captured["ctx"])
                for bits in itertools.product([0.0, 1.0], repeat=3))
            # a broken phase-3 evaluator returns inf for EVERY schedule,
            # which would make the optimality assert pass vacuously
            assert np.isfinite(best), \
                f"scenario {k}: no schedule evaluated successfully"
            assert res["stats"]["bb_incumbent"] == pytest.approx(
                best, rel=1e-3, abs=1e-5), \
                f"scenario {k}: T0={T0:.2f}, load={load:.0f}"

    def test_rounding_variant(self):
        backend = create_backend({
            "type": "jax_minlp",
            "model": {"class": SwitchedRoom},
            "discretization_options": {"method": "multiple_shooting"},
            "solver": {"max_iter": 60},
        })
        backend.setup_optimization(
            VariableReference(
                states=["T"], binary_controls=["on"],
                inputs=["load", "T_upper"],
                parameters=["C", "Q_cool", "s_T", "r_on"],
            ),
            time_step=300.0, prediction_horizon=8)
        result = backend.solve(0.0, {"T": 297.15})
        assert result["u0"]["on"] in (0.0, 1.0)

    def test_requires_binaries(self):
        backend = create_backend({
            "type": "jax_minlp",
            "model": {"class": SwitchedRoom},
        })
        with pytest.raises(ValueError, match="binary_controls"):
            backend.setup_optimization(
                VariableReference(states=["T"], controls=["on"]),
                time_step=300.0, prediction_horizon=4)

    def test_continuous_backend_rejects_binaries(self):
        backend = create_backend({
            "type": "jax",
            "model": {"class": SwitchedRoom},
        })
        with pytest.raises(NotImplementedError, match="minlp"):
            backend.setup_optimization(
                VariableReference(states=["T"], binary_controls=["on"]),
                time_step=300.0, prediction_horizon=4)
