"""Chaos harness (resilience/chaos.py) + fused-ADMM quarantine.

The injectors are seeded and deterministic — a chaos run is a pure
function of (seed, message/solve order) — and the fused engine's
quarantine keeps a 4-agent consensus step finite when one agent's theta
is NaN-poisoned, with ZERO additional retraces (pinned via the PR 1
``jax_retraces_total`` counter).
"""

import sys
import types
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.resilience.chaos import (
    AdmmDeathRule,
    BrokerRule,
    ChaosConfig,
    SolverRule,
    install_chaos,
)
from agentlib_mpc_tpu.runtime.broker import DataBroker
from agentlib_mpc_tpu.runtime.variables import AgentVariable

pytestmark = pytest.mark.chaos


def _fake_agent(broker=None, modules=None, agent_id="a"):
    return types.SimpleNamespace(
        id=agent_id,
        data_broker=broker if broker is not None else DataBroker(agent_id),
        modules=modules or {})


def _send_n(agent, n, alias="x"):
    got = []
    agent.data_broker.register_callback(alias, None,
                                        lambda v: got.append(v.value))
    for i in range(n):
        agent.data_broker.send_variable(
            AgentVariable(name=alias, alias=alias, value=float(i)))
    return got


class TestBrokerChaos:
    def test_drop_is_seeded_and_deterministic(self):
        runs = []
        for _ in range(2):
            agent = _fake_agent()
            ctl = install_chaos(agent, {
                "seed": 42, "broker": [{"alias": "x", "drop": 0.4}]})
            runs.append(tuple(_send_n(agent, 40)))
            assert ctl.count("drop") > 0
        assert runs[0] == runs[1]           # same seed → same fault train

        other = _fake_agent()
        install_chaos(other, {"seed": 43,
                              "broker": [{"alias": "x", "drop": 0.4}]})
        assert tuple(_send_n(other, 40)) != runs[0]

    def test_duplicate_and_delay(self):
        agent = _fake_agent()
        ctl = install_chaos(agent, {
            "seed": 7,
            "broker": [{"alias": "x", "duplicate": 0.3, "delay": 0.3}]})
        got = _send_n(agent, 50)
        assert ctl.count("duplicate") > 0 and ctl.count("delay") > 0
        ctl.flush()
        # nothing is lost (drop=0): every message arrives, some twice
        assert set(got) == {float(i) for i in range(50)}
        assert len(got) == 50 + ctl.count("duplicate")

    def test_untargeted_alias_passes_clean(self):
        agent = _fake_agent()
        install_chaos(agent, {"seed": 7,
                              "broker": [{"alias": "y", "drop": 1.0}]})
        assert _send_n(agent, 10, alias="x") == [float(i) for i in range(10)]

    def test_uninstall_restores_the_seam(self):
        agent = _fake_agent()
        ctl = install_chaos(agent, {"seed": 7,
                                    "broker": [{"alias": "x", "drop": 1.0}]})
        assert _send_n(agent, 5) == []
        ctl.uninstall()
        agent2got = []
        agent.data_broker.register_callback(
            "x", None, lambda v: agent2got.append(v.value))
        agent.data_broker.send_variable(
            AgentVariable(name="x", alias="x", value=1.0))
        assert agent2got == [1.0]


class TestSolverChaos:
    def _module_with_backend(self):
        def solve(now, variables):
            return {"u0": {"u": 0.5}, "traj": {"u": np.ones((4, 1))},
                    "stats": {"success": True}}

        backend = types.SimpleNamespace(solve=solve)
        module = types.SimpleNamespace(id="m", backend=backend)
        return module, backend

    def test_window_and_every(self):
        rule = SolverRule(every=2, start_call=3, n_calls=5)
        hits = [i for i in range(12) if rule.triggered(i)]
        assert hits == [3, 5, 7]

    def test_nan_mode_poisons_what_the_module_sees(self):
        module, backend = self._module_with_backend()
        agent = _fake_agent(modules={"m": module})
        ctl = install_chaos(agent, {
            "seed": 0,
            "solver": [{"target": "a/m", "mode": "nan", "every": 1,
                        "start_call": 1, "n_calls": 1}]})
        ok = backend.solve(0.0, {})
        assert ok["stats"]["success"] and np.isfinite(ok["u0"]["u"])
        poisoned = backend.solve(1.0, {})
        assert poisoned["stats"]["success"] is False
        assert np.isnan(poisoned["u0"]["u"])
        assert np.isnan(poisoned["traj"]["u"]).all()
        clean_again = backend.solve(2.0, {})
        assert clean_again["stats"]["success"]
        assert ctl.count("solver_nan") == 1

    def test_huge_mode_drives_out_of_bounds(self):
        module, backend = self._module_with_backend()
        agent = _fake_agent(modules={"m": module})
        install_chaos(agent, {
            "seed": 0, "solver": [{"target": "*", "mode": "huge"}]})
        res = backend.solve(0.0, {})
        assert res["u0"]["u"] > 1e9 and res["stats"]["success"] is False

    def test_target_mismatch_leaves_backend_alone(self):
        module, backend = self._module_with_backend()
        orig = backend.solve
        agent = _fake_agent(modules={"m": module})
        install_chaos(agent, {
            "seed": 0, "solver": [{"target": "other/m", "mode": "nan"}]})
        assert backend.solve is orig


class TestAdmmDeath:
    def test_silent_death_and_revival(self):
        calls = []
        module = types.SimpleNamespace(
            id="admm", optimize=lambda v: calls.append(v))
        agent = _fake_agent(modules={"admm": module}, agent_id="emp")
        ctl = install_chaos(agent, {
            "seed": 0,
            "admm": [{"agent": "emp", "die_at_call": 2,
                      "revive_at_call": 4}]})
        for i in range(6):
            module.optimize(i)
        assert calls == [0, 1, 4, 5]        # 2 and 3 swallowed silently
        assert ctl.count("admm_death") == 2


class TestConfigParsing:
    def test_from_dict_round_trip(self):
        cfg = ChaosConfig.from_dict({
            "seed": 3,
            "broker": [{"alias": "T", "drop": 0.1}],
            "solver": [{"target": "a/m", "mode": "fail"}],
            "admm": [{"agent": "emp", "die_at_call": 1}],
        })
        assert cfg.seed == 3
        assert cfg.broker[0] == BrokerRule(alias="T", drop=0.1)
        assert cfg.solver[0].mode == "fail"
        assert cfg.admm[0] == AdmmDeathRule(agent="emp", die_at_call=1)

    def test_unknown_keys_rejected(self):
        with pytest.raises(ValueError, match="unknown chaos option"):
            ChaosConfig.from_dict({"sover": []})


# -- fused-ADMM quarantine (acceptance criterion) ----------------------------

from conftest import make_tracker_model  # noqa: E402

from agentlib_mpc_tpu.ops.solver import SolverOptions  # noqa: E402
from agentlib_mpc_tpu.ops.transcription import transcribe  # noqa: E402
from agentlib_mpc_tpu.parallel.fused_admm import (  # noqa: E402
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
    stack_params,
)

N_AGENTS = 4


@pytest.fixture(scope="module")
def quarantine_setup():
    """4-agent fused consensus engine, warmed with one healthy round —
    compile/retrace hooks installed BEFORE the first trace so the
    retrace pin observes the whole lifetime."""
    from agentlib_mpc_tpu.utils.jax_setup import enable_compile_profiling

    telemetry.configure(enabled=True)
    enable_compile_profiling()
    Tracker = make_tracker_model(lb=-5.0, ub=5.0)
    ocp = transcribe(Tracker(), ["u"], N=5, dt=300.0,
                     method="multiple_shooting")
    group = AgentGroup(
        name="t", ocp=ocp, n_agents=N_AGENTS, couplings={"shared_u": "u"},
        solver_options=SolverOptions(tol=1e-8, max_iter=40))
    engine = FusedADMM([group], FusedADMMOptions(max_iterations=12, rho=2.0))
    thetas = stack_params([ocp.default_params(p=jnp.array([float(a)]))
                           for a in (1.0, 2.0, 3.0, 4.0)])
    state = engine.init_state([thetas])
    state, _, stats = engine.step(state, [thetas])
    assert int(np.asarray(stats.quarantined).sum()) == 0
    return engine, state, thetas, ocp


def _poison_theta(thetas, victim):
    return jax.tree.map(
        lambda leaf: leaf.at[victim].set(jnp.nan)
        if hasattr(leaf, "ndim") and leaf.ndim >= 1
        and leaf.shape[0] == N_AGENTS else leaf, thetas)


class TestQuarantine:
    def test_nan_warm_start_is_quarantined_and_recovers(self,
                                                        quarantine_setup):
        """A corrupted carry (NaN iterate) is quarantined and sanitized
        — the lane recovers within the first iterations and the round
        stays finite end to end, multipliers included."""
        engine, state, thetas, _ = quarantine_setup
        w_bad = state.w[0].at[1].set(jnp.nan)
        new_state, trajs, stats = engine.step(
            state._replace(w=(w_bad,)), [thetas])
        per_iter = np.asarray(stats.quarantined)
        assert per_iter.sum() >= 1
        # recovered: no quarantine events survive past the reset window
        assert per_iter[engine.options.quarantine_reset_after:].sum() == 0
        # EVERY carried leaf — lam included: a NaN substitution source
        # used to bake NaN into the multipliers through the consensus
        # mean while zbar/w/y/z stayed finite (review finding)
        for leaf in jax.tree.leaves(new_state):
            assert bool(jnp.all(jnp.isfinite(leaf)))
        assert bool(np.isfinite(np.asarray(trajs[0]["u"])).all())

    def test_lane_quarantined_attributes_the_sick_lane(self,
                                                       quarantine_setup):
        """The per-lane attribution (PR 8, the serving health ledger's
        input): the substitution keeps the victim's decoded trajectory
        finite, so ``lane_quarantined`` is the only signal naming WHICH
        lane was carried — it must finger exactly the victim."""
        engine, state, thetas, _ = quarantine_setup
        w_bad = state.w[0].at[1].set(jnp.nan)
        _, trajs, stats = engine.step(
            state._replace(w=(w_bad,)), [thetas])
        lane_q = np.asarray(stats.lane_quarantined[0])
        assert lane_q.shape == (N_AGENTS,)
        assert lane_q[1] >= 1                    # the victim is named
        assert (lane_q[[0, 2, 3]] == 0).all()    # nobody else is
        # the round total and the per-lane attribution agree
        assert lane_q.sum() == np.asarray(stats.quarantined).sum()
        # ... while the victim's decoded trajectory is finite — exactly
        # why the attribution (not the decode) must carry the signal
        assert bool(np.isfinite(np.asarray(trajs[0]["u"][1])).all())

    def test_nan_theta_keeps_the_fleet_finite(self, quarantine_setup):
        """One agent's NaN-poisoned parameters cannot poison the others
        through the consensus mean: means, multipliers and warm starts
        stay finite and the healthy agents' trajectories are unharmed."""
        engine, state, thetas, _ = quarantine_setup
        new_state, trajs, stats = engine.step(
            state, [_poison_theta(thetas, 1)])
        for leaf in jax.tree.leaves(new_state):
            assert bool(jnp.all(jnp.isfinite(leaf)))
        u = np.asarray(trajs[0]["u"])
        assert np.isfinite(u[[0, 2, 3]]).all()

    def test_poisoning_causes_zero_additional_retraces(self,
                                                       quarantine_setup):
        """The quarantine is pure jnp data flow: a poisoned round runs
        the SAME compiled program (pinned via the PR 1 retrace/compile
        counters)."""
        engine, state, thetas, _ = quarantine_setup
        reg = telemetry.metrics()
        engine.step(state, [thetas])            # warm reference round
        retraces = reg.counter("jax_retraces_total").total()
        compiles = reg.counter("jax_compiles_total").total()
        engine.step(state, [_poison_theta(thetas, 2)])
        assert reg.counter("jax_retraces_total").total() == retraces
        assert reg.counter("jax_compiles_total").total() == compiles

    def test_quarantine_counts_surface_in_telemetry(self, quarantine_setup):
        # poison the carry (NaN iterate) — the tracker NLP itself is
        # NaN-robust to a poisoned theta, so the warm start is the
        # injection point that reliably produces non-finite solutions
        engine, state, thetas, _ = quarantine_setup
        w_bad = state.w[0].at[3].set(jnp.nan)
        _, _, stats = engine.step(state._replace(w=(w_bad,)), [thetas])
        assert int(np.asarray(stats.quarantined).sum()) >= 1
        reg = telemetry.metrics()
        last = reg.get("admm_quarantined_agents_last_round", fleet="t")
        assert last is not None and last >= 1.0
        assert reg.get("admm_quarantined_agent_iters_total",
                       fleet="t") >= 1.0

    def test_quarantine_off_is_respected(self):
        """quarantine=False restores the raw engine (stats carry None)."""
        Tracker = make_tracker_model()
        ocp = transcribe(Tracker(), ["u"], N=3, dt=300.0,
                         method="multiple_shooting")
        group = AgentGroup(name="t", ocp=ocp, n_agents=2,
                           couplings={"shared_u": "u"},
                           solver_options=SolverOptions(tol=1e-6,
                                                        max_iter=15))
        engine = FusedADMM([group], FusedADMMOptions(
            max_iterations=3, quarantine=False))
        thetas = stack_params([ocp.default_params(p=jnp.array([1.0])),
                               ocp.default_params(p=jnp.array([2.0]))])
        state = engine.init_state([thetas])
        _, _, stats = engine.step(state, [thetas])
        assert stats.quarantined is None
