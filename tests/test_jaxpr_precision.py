"""Precision certifier (ISSUE 20): the forward error-propagation pass
that proves which subgraphs survive bf16/f32, and the certificate-gated
mixed-precision routing it cashes.

Three layers. (1) The handcrafted corpus: provable catastrophic
cancellation (the ``(x+1e8)-1e8`` mutation shape and the PR 19
epsilon-std 1e9-weight fold) must refute, benign arithmetic must prove,
opaque primitives must come back an honest "unknown" — never a fake
proof. (2) The solver seam: ``certify_solver_precision`` on the example
menu reproduces the checked-in ``[jaxpr.precision]`` pins. (3) The
engine seam + PR 3-style mutation: a FusedADMM build under
``precision="require"`` carries a proved certificate and digest; an
ill-conditioned subtraction injected into the transcribed objective
(evaluated inside the certified-bf16 eval_jac phase) makes the
certifier refute naming THIS file as the injected eqn's source, and
the ``"require"`` build refuses.
"""

import jax
import jax.numpy as jnp
import pytest

from agentlib_mpc_tpu.lint.jaxpr import (
    MIXED_NARROW_PHASES,
    PrecisionCertificate,
    certify_precision,
    certify_solver_precision,
    check_precision_budget,
)
from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.telemetry.profiler import phase_scope


@pytest.fixture
def f32():
    """The production regime. The lattice charges elementwise roundoff
    at the TRACED dtype, so the cancellation hazards these tests pin
    are live under an f32 trace (the CLI / TPU default: ``(x+1e8)-1e8``
    amplifies 2⁻²⁴ by κ ≈ 1e8 to ~6, refuting every narrow candidate)
    and neutered by the test suite's x64 conftest (the same κ amplifies
    2⁻⁵³ to ~2e-8, under every budget). Certify in f32 like the gate
    does."""
    from jax.experimental import enable_x64

    with enable_x64(False):
        yield


# --------------------------------------------------------------------------
# the handcrafted corpus
# --------------------------------------------------------------------------


class TestCorpus:
    def test_benign_affine_proves(self):
        cert = certify_precision(lambda x: 0.5 * x + 1.0,
                                 jnp.zeros((4,)))
        assert isinstance(cert, PrecisionCertificate)
        assert cert.proved
        assert cert.certified_dtype("unphased") in ("bf16", "f32")
        assert cert.precision_digest is not None

    def test_catastrophic_cancellation_refuted(self, f32):
        """The mutation shape: shifting through 1e8 and back makes every
        point of the seeded interval cancel — κ ≈ 2e8 amplifies the f32
        roundoff past any budget, and the hazard names THIS file."""

        def f(x):
            return (x + 1e8) - 1e8

        cert = certify_precision(f, jnp.zeros((4,)),
                                 seeds={0: (-1.0, 1.0)})
        assert cert.status == "refuted"
        assert cert.certified_dtype("unphased") == "f64"
        assert cert.precision_digest is None
        assert any("test_jaxpr_precision" in r for r in cert.refutations)

    def test_epsilon_std_fold_refused(self, f32):
        """The PR 19 hazard the pass exists to catch: an epsilon-std
        column folded into the weights bakes w=1e9 with a compensating
        1e9 bias — exact in f64, catastrophic cancellation in f32. The
        certifier must refuse it for every narrow dtype."""

        def folded(x):           # (x - mean) / std with std = 1e-9
            return x * 1e9 - 1e9

        cert = certify_precision(
            folded, jnp.zeros((4,)),
            seeds={0: (1.0 - 1e-9, 1.0 + 1e-9)})   # near-constant column
        assert cert.status == "refuted"
        assert cert.certified_dtype("unphased") == "f64"

    def test_sign_definite_sum_proves_narrow(self):
        """Same-sign accumulation has κ ≈ 1 (backward-error reading):
        a softplus-positive sum certifies below f64."""
        cert = certify_precision(
            lambda x: jnp.sum(jax.nn.softplus(x)), jnp.zeros((8,)),
            seeds={0: (-2.0, 2.0)})
        assert cert.proved

    def test_phase_scopes_partition_the_verdict(self, f32):
        """phase_scope annotations split the table: the cancellation
        sits in eval_jac only, so eval_jac refutes bf16 while the clean
        phase keeps its narrow verdict."""

        def f(x):
            with phase_scope("eval_jac"):
                a = (x + 1e8) - 1e8
            with phase_scope("line_search"):
                b = 0.5 * x + 1.0
            return a + b

        cert = certify_precision(f, jnp.zeros((4,)),
                                 seeds={0: (-1.0, 1.0)})
        assert cert.status == "refuted"          # eval_jac is required
        assert cert.certified_dtype("eval_jac") == "f64"
        assert cert.certified_dtype("line_search") in ("bf16", "f32")
        v = cert.verdict("eval_jac")
        assert v is not None and v.hazard

    def test_opaque_prim_is_unknown_not_proved(self):
        """Soundness boundary: an LU/triangular-solve has no
        per-primitive rule — the containing phase must come back
        "unknown", never silently certified."""

        def f(A, b):
            with phase_scope("eval_jac"):
                return jnp.linalg.solve(A, b)

        cert = certify_precision(f, jnp.eye(3), jnp.ones((3,)))
        assert cert.certified_dtype("eval_jac") == "unknown"
        assert cert.status == "unknown"
        assert cert.opaque

    def test_while_fixpoint_terminates_with_honest_widening(self):
        """A contractive while-loop carry reaches a fixpoint (or widens
        honestly) instead of diverging the walker."""

        def f(x):
            def body(c):
                i, v = c
                return i + 1, v * 0.5 + 1.0

            def cond(c):
                return c[0] < 50

            return jax.lax.while_loop(cond, body, (0, x))[1]

        cert = certify_precision(f, jnp.zeros((4,)),
                                 seeds={0: (-1.0, 1.0)})
        assert cert.status in ("proved", "refuted")
        assert cert.certified_dtype("unphased") != "unknown"


class TestBudgetRoundTrip:
    def _cert(self):
        def f(x):
            with phase_scope("line_search"):
                return 0.5 * x + 1.0

        return certify_precision(f, jnp.zeros((4,)),
                                 seeds={0: (-1.0, 1.0)})

    def test_matching_pin_is_clean(self):
        cert = self._cert()
        pin = ",".join(f"{v.phase}={v.certified_dtype}"
                       for v in cert.phases)
        assert check_precision_budget(cert, pin) == []

    def test_drift_in_either_direction_fails(self):
        cert = self._cert()
        v = cert.verdict("line_search")
        wrong = "f64" if v.certified_dtype != "f64" else "bf16"
        out = check_precision_budget(cert, f"line_search={wrong}")
        assert len(out) == 1 and "drifted" in out[0]

    def test_unparseable_pin_reported(self):
        out = check_precision_budget(self._cert(), "garbage")
        assert out and "unparseable" in out[0]


# --------------------------------------------------------------------------
# the solver seam: the example menu reproduces the checked-in pins
# --------------------------------------------------------------------------


class TestSolverMenu:
    def _certify(self, name):
        from agentlib_mpc_tpu.lint.jaxpr.examples import EXAMPLE_OCPS

        ex = next(e for e in EXAMPLE_OCPS if e.name == name)
        ocp = ex.build()
        theta = ocp.default_params()
        lb, ub = ocp.bounds(theta)
        return certify_solver_precision(ocp.nlp, theta, ocp.n_w, lb, ub)

    def test_linear_menu_entry_proves_mixed(self, f32):
        """The headline routing: the linear zone's IPM proves bf16 for
        the MXU phases, keeps factor/resolve honestly unknown (opaque
        LU), and the digest matches the lint gate's."""
        cert = self._certify("LinearRCZone/colloc-d1")
        assert cert.proved, cert.describe()
        for ph in MIXED_NARROW_PHASES:
            assert cert.certified_dtype(ph) == "bf16", cert.describe()
        assert cert.certified_dtype("factor") == "unknown"
        assert cert.precision_digest is not None
        from agentlib_mpc_tpu.lint.retrace_budget import load_budgets

        pin = load_budgets().get("jaxpr", {}).get(
            "precision", {}).get("expect", {}).get(
            "LinearRCZone/colloc-d1")
        assert pin, "[jaxpr.precision.expect] missing the menu pin"
        assert check_precision_budget(cert, pin) == []

    @pytest.mark.slow
    def test_oneroom_shooting_refuses_bf16_eval_jac(self, f32):
        """The one menu entry the router must NOT narrow: the
        exp-saturated shooting dynamics put a cancellation-prone sum in
        eval_jac — certified f32, status refuted, pinned in the budget
        file so the refusal itself is regression-gated."""
        cert = self._certify("OneRoom/shooting")
        assert cert.status == "refuted"
        assert cert.certified_dtype("eval_jac") == "f32"
        assert cert.refutations


# --------------------------------------------------------------------------
# the engine seam + the mutation direction
# --------------------------------------------------------------------------


def _tracker_group(n_agents, **solver_kw):
    from conftest import make_tracker_model

    from agentlib_mpc_tpu.ops.transcription import transcribe
    from agentlib_mpc_tpu.parallel.fused_admm import AgentGroup

    ocp = transcribe(make_tracker_model()(), ["u"], N=4, dt=300.0,
                     method="multiple_shooting")
    return AgentGroup(
        name="fleet", ocp=ocp, n_agents=n_agents,
        couplings={"shared_u": "u"},
        solver_options=SolverOptions(max_iter=25, **solver_kw),
        qp_fast_path="off")


class TestEngineSeam:
    def test_require_build_carries_proof_and_digest(self):
        from agentlib_mpc_tpu.parallel.fused_admm import (
            FusedADMM,
            FusedADMMOptions,
        )

        engine = FusedADMM(
            [_tracker_group(2)],
            FusedADMMOptions(max_iterations=8, rho=2.0),
            precision_certify="require")
        cert = engine.precision_certificate
        assert cert is not None and cert.proved, cert.describe()
        assert engine.precision_digest == cert.precision_digest
        assert engine.precision_digest is not None

    def test_injected_cancellation_refused_under_require(self, f32):
        """PR 3's source-surgery pattern: wrap the transcribed NLP's
        objective with a bounded term shifted through 1e8 and back —
        exact algebra, but tanh's [-1, 1] output interval makes the
        cancellation's κ ≈ 1e8 PROVABLE at every seed point. The primal
        objective evaluates under ``phase_scope("eval_jac")`` inside
        the fused step, so the certifier must refute the narrow routing
        naming the injected eqn's source (THIS file), and
        ``precision="require"`` must refuse the build."""
        import dataclasses

        from conftest import make_tracker_model

        from agentlib_mpc_tpu.ops.transcription import transcribe
        from agentlib_mpc_tpu.parallel.fused_admm import (
            AgentGroup,
            FusedADMM,
            FusedADMMOptions,
        )

        ocp = transcribe(make_tracker_model()(), ["u"], N=4, dt=300.0,
                         method="multiple_shooting")
        real_f = ocp.nlp.f

        def sabotaged_f(w, theta):
            # the regression: a bounded quantity shifted through 1e8
            # and back — exact in f64, catastrophic for every narrow
            # candidate
            return real_f(w, theta) + ((jnp.tanh(w[0]) + 1e8) - 1e8)

        ocp = dataclasses.replace(
            ocp, nlp=ocp.nlp._replace(f=sabotaged_f))
        group = AgentGroup(
            name="fleet", ocp=ocp, n_agents=2,
            couplings={"shared_u": "u"},
            solver_options=SolverOptions(max_iter=25,
                                         precision="require"),
            qp_fast_path="off")
        with pytest.raises(ValueError) as ei:
            FusedADMM([group],
                      FusedADMMOptions(max_iterations=8, rho=2.0))
        msg = str(ei.value)
        assert "REFUTED" in msg
        assert "test_jaxpr_precision" in msg    # the injected eqn
        assert "eval_jac" in msg
