"""Integration: the one-room cooling MPC as a two-agent MAS.

This is the reference's flagship closed-loop wiring
(``examples/one_room_mpc/physical/simple_mpc.py``: AGENT_MPC + AGENT_SIM on
a LocalMASAgency) rebuilt on the native runtime: MPC agent solves and
broadcasts ``mDot``; simulator agent integrates the plant and broadcasts
its temperature back under alias ``T``.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from examples.one_room_mpc import OneRoom
from agentlib_mpc_tpu.runtime.mas import LocalMAS
import agentlib_mpc_tpu.modules  # noqa: F401 - registers module types

UB = 295.15

AGENT_MPC = {
    "id": "myMPCAgent",
    "modules": [
        {"module_id": "Ag1Com", "type": "local_broadcast"},
        {
            "module_id": "myMPC",
            "type": "mpc",
            "optimization_backend": {
                "type": "jax",
                "model": {"class": OneRoom},
                "discretization_options": {
                    "collocation_order": 2,
                    "collocation_method": "legendre",
                },
                "solver": {"max_iter": 60},
            },
            "time_step": 300,
            "prediction_horizon": 15,
            "parameters": [
                {"name": "s_T", "value": 0.001},
                {"name": "r_mDot", "value": 0.01},
            ],
            "inputs": [
                {"name": "T_in", "value": 290.15},
                {"name": "load", "value": 150},
                {"name": "T_upper", "value": UB},
            ],
            "controls": [{"name": "mDot", "value": 0.02, "ub": 0.05, "lb": 0}],
            "outputs": [{"name": "T_out"}],
            "states": [
                {"name": "T", "value": 298.16, "ub": 303.15, "lb": 288.15,
                 "alias": "T", "source": "SimAgent"},
            ],
        },
    ],
}

AGENT_SIM = {
    "id": "SimAgent",
    "modules": [
        {"module_id": "Ag1Com", "type": "local_broadcast"},
        {
            "module_id": "room",
            "type": "simulator",
            "model": {"class": OneRoom,
                      "states": [{"name": "T", "value": 298.16}]},
            "t_sample": 10,
            "outputs": [{"name": "T_out", "value": 298, "alias": "T"}],
            "inputs": [{"name": "mDot", "value": 0.02, "alias": "mDot"}],
        },
    ],
}


@pytest.fixture(scope="module")
def results():
    mas = LocalMAS([AGENT_MPC, AGENT_SIM], env={"rt": False})
    mas.run(until=3600)
    res = mas.get_results()
    res["_mas"] = mas
    return res


def test_results_shape(results):
    mpc_df = results["myMPCAgent"]["myMPC"]
    assert mpc_df.index.names == ["time", "grid"]
    assert ("variable", "T") in mpc_df.columns
    assert ("variable", "mDot") in mpc_df.columns
    sim_df = results["SimAgent"]["room"]
    assert "T_out" in sim_df.columns and "mDot" in sim_df.columns


def test_room_cools_toward_band(results):
    sim_df = results["SimAgent"]["room"]
    assert sim_df["T_out"].iloc[-1] < 296.2
    assert sim_df["T_out"].iloc[-1] < sim_df["T_out"].iloc[0]


def test_actuation_crosses_agents(results):
    """The mDot the simulator integrates must be the MPC's command."""
    sim_df = results["SimAgent"]["room"]
    assert sim_df["mDot"].std() > 0  # changed over time
    assert sim_df["mDot"].max() <= 0.05 + 1e-9


def test_solver_stats_recorded(results):
    mas = results["_mas"]
    stats = mas.agents["myMPCAgent"].get_module("myMPC").solver_stats()
    assert stats is not None
    assert bool(stats["success"].all())
    assert (stats["iterations"] < 60).all()


def test_mpc_sees_simulated_state(results):
    """The MPC's recorded x trajectory must track the simulator (not its
    stale initial value)."""
    mpc_df = results["myMPCAgent"]["myMPC"]
    t_last = mpc_df.index.get_level_values("time").max()
    x0_last = mpc_df.loc[t_last][("variable", "T")].iloc[0]
    sim_df = results["SimAgent"]["room"]
    sim_at = sim_df["T_out"][sim_df.index <= t_last].iloc[-1]
    assert abs(x0_last - sim_at) < 0.2


def test_simulator_parameter_override_via_module_config():
    """Module-level parameter values must reach the integrator (review
    regression: defaults were always used)."""
    from agentlib_mpc_tpu.runtime.mas import LocalMAS

    def make(C):
        return {"id": "s", "modules": [{
            "module_id": "room", "type": "simulator",
            "model": {"class": OneRoom,
                      "states": [{"name": "T", "value": 298.16}]},
            "t_sample": 100,
            "parameters": [{"name": "C", "value": C}],
            "outputs": [{"name": "T_out"}],
            "inputs": [{"name": "mDot", "value": 0.05}],
        }]}

    res = {}
    for C in (1e5, 2e4):
        mas = LocalMAS([make(C)])
        mas.run(until=600)
        res[C] = mas.get_results()["s"]["room"]["T_out"].iloc[-1]
    # smaller capacity → faster cooling → lower final temperature
    assert res[2e4] < res[1e5] - 0.1


def test_simulator_timestamps_match_state_validity():
    """Measurements are published at t+dt, the time the integrated state is
    valid (review regression: published at t with the t+dt state)."""
    from agentlib_mpc_tpu.runtime.mas import LocalMAS
    from agentlib_mpc_tpu.runtime.module import BaseModule, register_module

    received = []

    @register_module("_test_listener")
    class Listener(BaseModule):
        def register_callbacks(self):
            self.agent.data_broker.register_callback(
                "T", None, lambda v: received.append((v.timestamp, v.value)))

    mas = LocalMAS([
        {"id": "s", "modules": [{
            "module_id": "room", "type": "simulator",
            "model": {"class": OneRoom,
                      "states": [{"name": "T", "value": 298.16}]},
            "t_sample": 50,
            "outputs": [{"name": "T_out", "alias": "T"}],
            "inputs": [{"name": "mDot", "value": 0.02}]}]},
        {"id": "l", "modules": [{"module_id": "x", "type": "_test_listener"}]},
    ])
    mas.run(until=200)
    assert received, "listener got no measurements"
    times = [t for t, _ in received]
    assert times[0] == 50.0 and times == sorted(times)
